"""Continuous-batching serving engine (ISSUE 8).

Three layers, leanest first: jax-free scheduler unit tests over
scripted backends (refill ordering, admission control, EOS retirement,
streaming callback order, per-request quarantine, stall watchdog),
device-free telemetry plumbing (histogram quantiles + gang
aggregation), then ONE engine-on-CPU equivalence test over
``LlamaConfig.tiny`` (slot prefill/decode + staggered refill must be
token-identical to the static ``generate()`` path) and the slow
serve-smoke e2e.
"""

import glob
import os
import re
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.runner import telemetry
from sparkdl_tpu.serving import (DeadlineExceeded, EngineStopped,
                                 GenerationEngine, QueueFullError,
                                 RequestCancelled, RequestQuarantined,
                                 RequestRejected, ServingStallError,
                                 StubBackend, bucket_length)


class RecordingBackend(StubBackend):
    """Stub that records the (prompt, slot) order of every prefill
    start — the scheduler-ordering observable on both paths (chunked
    admission arms via ``begin_prefill``, the blocking fallback goes
    straight to ``prefill``)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.prefill_log: list[tuple[tuple, int]] = []

    def prefill(self, slot, prompt, bucket):
        self.prefill_log.append((tuple(prompt), slot))
        return super().prefill(slot, prompt, bucket)

    def begin_prefill(self, slot, prompt, chunk):
        self.prefill_log.append((tuple(prompt), slot))
        return super().begin_prefill(slot, prompt, chunk)


class ChunkRecordingBackend(StubBackend):
    """Records every ``prefill_chunk`` / ``step`` call (offsets and
    interleaving — the stall-free observables)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.calls: list[tuple] = []  # ("chunk", slot, offset, n_valid)
        #                              | ("step", n_active)

    def prefill_chunk(self, slot, chunk_tokens, offset, n_valid,
                          window=None):
        self.calls.append(("chunk", slot, offset, n_valid))
        return super().prefill_chunk(slot, chunk_tokens, offset, n_valid)

    def step(self, active_slots):
        self.calls.append(("step", len(list(active_slots))))
        return super().step(active_slots)


# ---------------------------------------------------------------------------
# jax-free scheduler unit tests
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_fifo_refill_order_lowest_slot_first(self):
        be = RecordingBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        reqs = [eng.submit([i, i + 1], max_new_tokens=3) for i in range(5)]
        eng.run_until_idle()
        # admitted strictly in submission order
        assert [p for p, _ in be.prefill_log] == \
            [tuple(r.prompt) for r in reqs]
        # first two land on slots 0 and 1 (lowest free slot first)
        assert [s for _, s in be.prefill_log[:2]] == [0, 1]
        for r in reqs:
            assert r.result(1) and r.finish_reason == "length"
        assert eng.snapshot()["completed"] == 5

    def test_requests_overlap_across_slots(self):
        """A freed slot refills while the other slot's request is still
        decoding — the batch never drains."""
        be = StubBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        long = eng.submit([1], max_new_tokens=12)
        short = eng.submit([2], max_new_tokens=2)
        third = eng.submit([3], max_new_tokens=2)
        eng.run_until_idle()
        # third was admitted into short's freed slot BEFORE long retired
        assert third.t_admit < long.t_done
        assert eng.snapshot()["peak_slots_busy"] == 2
        assert all(r.state == "done" for r in (long, short, third))

    def test_stream_callback_order_first_token_included(self):
        per_req: dict = {}
        be = StubBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        reqs = [eng.submit([i + 1, 7], max_new_tokens=4,
                           stream_cb=lambda r, t:
                           per_req.setdefault(r.id, []).append(t))
                for i in range(3)]
        eng.run_until_idle()
        for r in reqs:
            assert per_req[r.id] == r.result(1)  # every token, in order
            assert len(per_req[r.id]) == 4

    def test_broken_callback_never_kills_the_loop(self):
        def boom(r, t):
            raise RuntimeError("client bug")
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        r = eng.submit([1], max_new_tokens=3, stream_cb=boom)
        eng.run_until_idle()
        assert r.result(1) and eng.snapshot()["callback_errors"] == 3

    def test_eos_retires_slot_early(self):
        class EosAt2(StubBackend):
            def _tok(self, key, n):
                return 9 if n == 2 else (key + n) % self.vocab_size

        eng = GenerationEngine(EosAt2(1, 64, vocab_size=100), eos_id=9)
        r = eng.submit([5], max_new_tokens=40)
        eng.run_until_idle()
        out = r.result(1)
        assert out[-1] == 9 and len(out) == 3  # eos included, then stop
        assert r.finish_reason == "eos"

    def test_admission_rejects(self):
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=100),
                               min_bucket=8)
        with pytest.raises(RequestRejected, match="empty"):
            eng.submit([], max_new_tokens=4)
        with pytest.raises(RequestRejected, match="outside vocab"):
            eng.submit([5, 100], max_new_tokens=4)
        with pytest.raises(RequestRejected, match="exceeds max_len"):
            eng.submit(list(range(1, 40)), max_new_tokens=32)  # 64+32>64
        with pytest.raises(RequestRejected, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)
        assert eng.snapshot()["rejected"] == 4

    def test_queue_backpressure(self):
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100),
                               queue_capacity=1)
        eng.submit([1], max_new_tokens=2)
        with pytest.raises(QueueFullError):
            eng.submit([2], max_new_tokens=2, block=False)
        with pytest.raises(QueueFullError):
            eng.submit([2], max_new_tokens=2, timeout=0.05)
        snap = eng.snapshot()
        assert snap["rejected"] == 2 and snap["queue_depth"] == 1
        eng.run_until_idle()
        # space freed -> accepted again
        assert eng.submit([3], max_new_tokens=2, block=False)
        eng.run_until_idle()

    def test_prefill_retry_then_success(self):
        class FlakyOnce(StubBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.fails = 0

            def prefill(self, slot, prompt, bucket):
                if prompt[0] == 42 and self.fails == 0:
                    self.fails += 1
                    raise RuntimeError("transient")
                return super().prefill(slot, prompt, bucket)

            def prefill_chunk(self, slot, chunk_tokens, offset, n_valid,
                          window=None):
                if chunk_tokens[0] == 42 and self.fails == 0:
                    self.fails += 1
                    raise RuntimeError("transient")
                return super().prefill_chunk(slot, chunk_tokens, offset,
                                             n_valid)

        eng = GenerationEngine(FlakyOnce(1, 64, vocab_size=100), retries=1)
        r = eng.submit([42], max_new_tokens=3)
        eng.run_until_idle()
        assert r.result(1) and r.failures == 1
        assert eng.snapshot()["prefill_retries"] == 1

    def test_prefill_quarantine_after_repeated_failure(self):
        class Poison(StubBackend):
            def prefill(self, slot, prompt, bucket):
                if prompt[0] == 99:
                    raise RuntimeError("bad prompt payload")
                return super().prefill(slot, prompt, bucket)

            def prefill_chunk(self, slot, chunk_tokens, offset, n_valid,
                          window=None):
                if offset == 0 and chunk_tokens[0] == 99:
                    raise RuntimeError("bad prompt payload")
                return super().prefill_chunk(slot, chunk_tokens, offset,
                                             n_valid)

        eng = GenerationEngine(Poison(2, 64, vocab_size=100), retries=2)
        good = eng.submit([1, 2], max_new_tokens=4)
        bad = eng.submit([99], max_new_tokens=4)
        also_good = eng.submit([3], max_new_tokens=4)
        eng.run_until_idle()
        # the poisoned request is evicted, not the gang
        assert good.result(1) and also_good.result(1)
        assert bad.state == "failed" and bad.failures == 3
        with pytest.raises(RequestQuarantined):
            bad.result(1)
        snap = eng.snapshot()
        assert snap["quarantined"] == 1 and snap["completed"] == 2

    def test_step_failure_evicts_newest_suspect(self):
        class StepPoison(StubBackend):
            def step(self, active):
                # key = sum(prompt) + len(prompt); [99] -> 100
                if any(self._state[s][0] == 100 for s in active):
                    raise RuntimeError("poisoned decode")
                return super().step(active)

        eng = GenerationEngine(StepPoison(2, 64, vocab_size=200),
                               retries=1)
        survivor = eng.submit([1, 2], max_new_tokens=6)
        poison = eng.submit([99], max_new_tokens=6)
        eng.run_until_idle()
        assert survivor.result(1) and survivor.finish_reason == "length"
        assert poison.state == "failed"
        snap = eng.snapshot()
        assert snap["quarantined"] == 1 and snap["step_retries"] >= 1

    def test_sole_occupant_eviction_keeps_engine_alive(self):
        """A poisoned request that is the ONLY one in flight is evicted
        exactly like a co-resident one — the engine survives and keeps
        serving the queue (eviction must never be gang-fatal)."""
        class StepPoison(StubBackend):
            def step(self, active):
                if any(self._state[s][0] == 100 for s in active):  # [99]
                    raise RuntimeError("poisoned decode")
                return super().step(active)

        eng = GenerationEngine(StepPoison(2, 64, vocab_size=200),
                               retries=1)
        poison = eng.submit([99], max_new_tokens=6)  # alone in flight
        eng.run_until_idle()
        assert poison.state == "failed"
        assert eng.snapshot()["quarantined"] == 1
        # engine alive: a new request completes normally
        after = eng.submit([1, 2], max_new_tokens=4)
        eng.run_until_idle()
        assert after.result(1) and after.finish_reason == "length"

    def test_serving_fatal_error_skips_retry_and_fails_over(self):
        """An error flagged ``serving_fatal`` (backend.SlotCacheLost:
        the donated cache was consumed — retrying would read a deleted
        buffer) skips the retry/evict ladder entirely and routes
        through the ISSUE 19 failover seam: the backend is rebuilt and
        every live request re-admitted via the preemption-resume path.
        A decode-only fault still gains one token per cycle (the resume
        prefill emits the next token), so no budget trips and the
        workload COMPLETES — token-identical to a clean run."""
        class CacheGone(RuntimeError):
            serving_fatal = True

        class LostCache(StubBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.rebuilds = 0

            def step(self, active):
                raise CacheGone("cache consumed mid-execution")

            def rebuild(self):
                self.rebuilds += 1
                super().rebuild()

        be = LostCache(2, 64, vocab_size=100)
        # budget = 2 chunks so BOTH requests prefill (and so progress)
        # every failover cycle
        eng = GenerationEngine(be, retries=3, prefill_chunk=8,
                               prefill_budget=16)
        a = eng.submit([1], max_new_tokens=3)
        b = eng.submit([2], max_new_tokens=3)
        eng.run_until_idle()
        snap = eng.snapshot()
        # no retries burned, nobody evicted/quarantined — straight over
        assert snap["step_retries"] == 0 and snap["quarantined"] == 0
        assert snap["failovers"] >= 2 and be.rebuilds == snap["failovers"]
        assert snap["failover"]["state"] == "recovered"
        assert snap["failover_resumed"] >= 2
        for r in (a, b):
            assert len(r.result(1)) == 3 and r.finish_reason == "length"
            assert r.failovers > 0 and r.delivered == 3
        # exactly-once resume: the interrupted run's streams are
        # bit-identical to an uninterrupted engine's
        eng2 = GenerationEngine(StubBackend(2, 64, vocab_size=100))
        a2 = eng2.submit([1], max_new_tokens=3)
        b2 = eng2.submit([2], max_new_tokens=3)
        eng2.run_until_idle()
        assert a.tokens == a2.tokens and b.tokens == b2.tokens

    def test_fatal_error_without_rebuild_fails_closed(self):
        """A backend with no ``rebuild`` hook keeps the pre-ISSUE-19
        posture: serving-fatal ⇒ engine dies, pending requests failed
        with EngineStopped, later submits rejected."""
        class CacheGone(RuntimeError):
            serving_fatal = True

        class LostCache(StubBackend):
            rebuild = None  # not failover-capable

            def step(self, active):
                raise CacheGone("cache consumed mid-execution")

        eng = GenerationEngine(LostCache(2, 64, vocab_size=100),
                               retries=3)
        a = eng.submit([1], max_new_tokens=5)
        b = eng.submit([2], max_new_tokens=5)
        with pytest.raises(CacheGone):
            eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["step_retries"] == 0 and snap["quarantined"] == 0
        assert snap["failovers"] == 0
        for r in (a, b):
            assert r.state == "failed" and \
                isinstance(r.error, EngineStopped)
        with pytest.raises(EngineStopped):
            eng.submit([3], max_new_tokens=2)

    def test_stall_watchdog_names_stage_and_fails_pending(self):
        class Wedged(StubBackend):
            rebuild = None  # not failover-capable: fail closed

            def step(self, active):
                time.sleep(3)
                return super().step(active)

        eng = GenerationEngine(Wedged(1, 64, vocab_size=100), stall_s=0.2)
        r = eng.submit([1], max_new_tokens=5)
        with pytest.raises(ServingStallError, match="decode_step"):
            eng.run_until_idle()
        assert r.state == "failed" and isinstance(r.error, EngineStopped)

    def test_stall_fails_over_when_backend_is_rebuildable(self):
        """A stall-watchdog fire on a rebuildable backend is a failover
        cause, not a death sentence: the wedged call is abandoned (the
        watchdog pool is discarded so the rebuild never queues behind
        it) and the workload completes after the rebuild."""
        class WedgedOnce(StubBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.wedged = False

            def step(self, active):
                if not self.wedged:
                    self.wedged = True
                    time.sleep(0.8)
                    # late return from the abandoned stint: report
                    # nothing, touch no chain state — the engine
                    # discarded this future anyway
                    return [0] * self.num_slots
                return super().step(active)

        eng = GenerationEngine(WedgedOnce(1, 64, vocab_size=100),
                               stall_s=0.1)
        r = eng.submit([1], max_new_tokens=4)
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["failovers"] == 1
        assert snap["failover"]["state"] == "recovered"
        assert len(r.result(1)) == 4 and r.failovers == 1

    def test_failover_budget_exhaustion_fails_closed_classified(self):
        """Zero-progress failovers (the fault hits before ANY token)
        burn the engine streak; past SPARKDL_SERVE_FAILOVER_BUDGET the
        engine fails closed with the budget named in the error."""
        class CacheGone(RuntimeError):
            serving_fatal = True

        class DeadOnArrival(StubBackend):
            def finish_prefill(self, *a, **kw):
                raise CacheGone("cache consumed mid-prefill")

        eng = GenerationEngine(DeadOnArrival(1, 64, vocab_size=100),
                               failover_budget=2)
        r = eng.submit([1], max_new_tokens=4)
        with pytest.raises(CacheGone):
            eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["failovers"] == 2  # budget spent before the trip
        assert snap["failover"]["state"] == "exhausted"
        assert r.state == "failed" and isinstance(r.error, EngineStopped)
        assert "failover budget exhausted" in str(r.error)
        assert "SPARKDL_SERVE_FAILOVER_BUDGET=2" in str(r.error)

    def test_per_request_failover_quarantine_spares_the_fleet(self):
        """A single request that personally triggers the fault (and so
        never gains a token across failovers) is quarantined
        individually; innocent co-resident requests keep completing —
        and the engine survives, because the poison request's removal
        restores progress."""
        class CacheGone(RuntimeError):
            serving_fatal = True

        class PoisonPrompt(StubBackend):
            def finish_prefill(self, slot, prompt, last_tok,
                               aligned_len, commit=True):
                if list(prompt)[:1] == [99]:
                    raise CacheGone("poison prompt")
                return super().finish_prefill(slot, prompt, last_tok,
                                              aligned_len, commit=commit)

        eng = GenerationEngine(PoisonPrompt(2, 64, vocab_size=100),
                               failover_budget=2, prefill_chunk=8,
                               prefill_budget=16)
        good = eng.submit([1], max_new_tokens=3)
        bad = eng.submit([99], max_new_tokens=3)
        eng.run_until_idle()
        assert len(good.result(1)) == 3
        assert bad.state == "failed" and \
            isinstance(bad.error, RequestQuarantined)
        snap = eng.snapshot()
        assert snap["failover_quarantined"] == 1
        assert snap["failover"]["quarantined_total"] == 1

    def test_stop_now_fails_pending_drain_completes(self):
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100,
                                           step_s=0.002)).start()
        rs = [eng.submit([i + 1], max_new_tokens=4) for i in range(4)]
        eng.stop(drain=True, timeout=30)
        assert all(r.state == "done" for r in rs)
        eng2 = GenerationEngine(StubBackend(1, 64, vocab_size=100,
                                            step_s=0.05)).start()
        rs2 = [eng2.submit([i + 1], max_new_tokens=40) for i in range(3)]
        eng2.stop(drain=False, timeout=30)
        assert any(r.state == "failed" and
                   isinstance(r.error, EngineStopped) for r in rs2)
        with pytest.raises(EngineStopped):
            eng2.submit([9], max_new_tokens=2)

    def test_concurrent_submitters_all_complete(self):
        eng = GenerationEngine(StubBackend(4, 64, vocab_size=100),
                               queue_capacity=8).start()
        handles, hlock = [], threading.Lock()

        def client(base):
            for i in range(6):
                h = eng.submit([base, i + 1], max_new_tokens=3)
                with hlock:
                    handles.append(h)
                h.result(timeout=30)

        threads = [threading.Thread(target=client, args=(b,))
                   for b in (1, 2, 3, 4, 5, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        eng.stop(drain=True, timeout=30)
        assert len(handles) == 36
        assert all(h.state == "done" for h in handles)  # nothing starves

    def test_queue_capacity_floor(self):
        # capacity 0 would make every blocking submit spin forever
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100),
                               queue_capacity=0)
        assert eng.queue_capacity == 1
        assert eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()

    def test_bucket_length_contract(self):
        assert bucket_length(1, 8) == 8
        assert bucket_length(8, 8) == 8
        assert bucket_length(9, 8) == 16
        assert bucket_length(33, 8) == 64
        with pytest.raises(ValueError):
            bucket_length(0, 8)


# ---------------------------------------------------------------------------
# deadlines + cancellation (ISSUE 19)
# ---------------------------------------------------------------------------

class TestDeadlinesAndCancel:
    def test_cancel_running_prefilling_and_queued(self):
        """``Request.cancel()`` is honored at the next iteration
        boundary in every live state — RUNNING, PREFILLING (multi-chunk
        prompt), and still-queued — freeing the slot each time, and a
        cancelled request is counted ``cancelled``, never
        ``quarantined``."""
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100),
                               prefill_chunk=4)
        running = eng.submit([1, 2, 3], max_new_tokens=50)
        prefilling = eng.submit(list(range(16)), max_new_tokens=5)
        queued = eng.submit([7], max_new_tokens=5)
        for _ in range(20):
            eng.step()
            if running.state == "running":
                break
        assert running.state == "running"
        running.cancel()
        eng.step()  # boundary reap frees the only slot
        assert running.state == "failed"
        assert running.finish_reason == "cancelled"
        assert isinstance(running.error, RequestCancelled)
        # the 16-token prompt admits into the freed slot: 4 chunks, so
        # after one step it is mid-prefill
        for _ in range(20):
            if prefilling.state == "prefilling":
                break
            eng.step()
        assert prefilling.state == "prefilling"
        prefilling.cancel()
        queued.cancel()  # cancelled straight out of the queue
        eng.step()  # one boundary reaps both (before any admission)
        assert prefilling.state == "failed" and \
            prefilling.finish_reason == "cancelled"
        assert queued.state == "failed" and queued.t_admit is None
        snap = eng.snapshot()
        assert snap["cancelled"] == 3 and snap["quarantined"] == 0
        assert snap["failover_quarantined"] == 0
        after = eng.submit([5], max_new_tokens=3)  # engine healthy
        eng.run_until_idle()
        assert len(after.result(1)) == 3

    def test_deadline_mid_chunked_prefill_releases_blocks_and_radix(self):
        """A deadline expiring mid-chunked-prefill releases every
        reserved KV block and leaves NO radix entry (the commit only
        happens at finish_prefill, which the victim never reaches)."""
        be = StubBackend(2, 64, vocab_size=100, block_size=4,
                         prefix_cache_bytes=1 << 20)
        eng = GenerationEngine(be, prefill_chunk=4)
        free0 = be.pool_stats()["blocks_free"]
        r = eng.submit(list(range(1, 17)), max_new_tokens=5,
                       deadline_s=0.05)
        eng.step()  # admit + reserve blocks + chunk 1 of 4
        assert r.state == "prefilling"
        assert be.pool_stats()["blocks_free"] < free0
        time.sleep(0.06)
        eng.step()  # boundary reap: slot + blocks released
        assert r.state == "failed" and r.finish_reason == "deadline"
        assert isinstance(r.error, DeadlineExceeded)
        assert be.pool_stats()["blocks_free"] == free0
        assert be.pool_stats()["radix_blocks"] == 0  # no commit rolled in
        snap = eng.snapshot()
        assert snap["cancelled"] == 1 and snap["quarantined"] == 0

    def test_deadline_env_default_applies(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_SERVE_DEADLINE_S", "0.03")
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100,
                                           step_s=0.01))
        assert eng.default_deadline_s == pytest.approx(0.03)
        r = eng.submit([1], max_new_tokens=50)
        eng.run_until_idle()
        assert r.finish_reason == "deadline"
        assert isinstance(r.error, DeadlineExceeded)
        assert 0 < len(r.tokens) < 50

    def test_deadline_honored_mid_verify_window(self):
        """Speculation can emit several tokens per iteration; the emit
        loop re-checks the deadline BETWEEN window tokens, so an expiry
        mid-verify-window stops the stream exactly at the cut."""
        cut = 10

        def cb(req, tok):
            if len(req.tokens) == cut:
                req.t_deadline = time.time() - 1.0  # already expired

        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               spec_k=4)
        h = eng.submit([1, 2, 3], max_new_tokens=40, stream_cb=cb)
        eng.run_until_idle()
        assert eng.snapshot()["spec_verifies"] >= 1  # speculation ran
        assert h.state == "failed" and h.finish_reason == "deadline"
        assert isinstance(h.error, DeadlineExceeded)
        assert len(h.tokens) == cut and h.delivered == cut

    def test_cancel_honored_mid_verify_window(self):
        cut = 8

        def cb(req, tok):
            if len(req.tokens) == cut:
                req.cancel()

        eng = GenerationEngine(StubBackend(2, 64, vocab_size=8),
                               spec_k=4)
        h = eng.submit([1, 2, 3], max_new_tokens=40, stream_cb=cb)
        eng.run_until_idle()
        assert h.state == "failed" and h.finish_reason == "cancelled"
        assert isinstance(h.error, RequestCancelled)
        assert len(h.tokens) == cut and h.delivered == cut
        assert eng.snapshot()["quarantined"] == 0


# ---------------------------------------------------------------------------
# serving failure taxonomy drift-guard (ISSUE 19)
# ---------------------------------------------------------------------------

class TestFailureTaxonomy:
    """Every exception class defined under ``sparkdl_tpu/serving/``
    must carry an explicit verdict in
    ``runner.failures.SERVING_CLASS_VERDICTS`` — the same static
    drift-guard posture as ``check_env_docs``, so failover vs retry vs
    quarantine routing can never silently default for a new error.
    Text-based (not import-based): ``serving/backend.py`` imports jax
    at module scope, and this guard must hold in any environment."""

    _CLASS_RE = re.compile(r"^class\s+(\w+)\(([^)]*)\):", re.MULTILINE)
    _BUILTIN_EXC = {"BaseException", "Exception", "RuntimeError",
                    "ValueError", "KeyError", "OSError", "TimeoutError"}

    def _serving_exception_classes(self) -> set:
        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "sparkdl_tpu", "serving")
        bases_of: dict = {}
        for path in glob.glob(os.path.join(root, "*.py")):
            with open(path, encoding="utf-8") as f:
                for name, bases in self._CLASS_RE.findall(f.read()):
                    bases_of[name] = [b.strip().split(".")[-1]
                                      for b in bases.split(",")
                                      if b.strip()]
        exc: set = set()
        changed = True
        while changed:  # transitive: FooError(ServingError) counts too
            changed = False
            for name, bases in bases_of.items():
                if name not in exc and any(
                        b in self._BUILTIN_EXC or b in exc
                        for b in bases):
                    exc.add(name)
                    changed = True
        return exc

    def test_every_serving_exception_has_a_verdict(self):
        from sparkdl_tpu.runner import failures
        classes = self._serving_exception_classes()
        # the grep itself works (engine + backend exceptions found)
        assert "ServingError" in classes and "SlotCacheLost" in classes
        assert "BlockExhausted" in classes
        missing = sorted(c for c in classes
                         if c not in failures.SERVING_CLASS_VERDICTS)
        assert not missing, (
            f"serving exception classes without a "
            f"failures.SERVING_CLASS_VERDICTS entry: {missing}")
        for name in classes:
            assert failures.SERVING_CLASS_VERDICTS[name] in (
                "retryable", "fatal")

    def test_classify_routes_serving_exceptions(self):
        from sparkdl_tpu.runner import failures
        from sparkdl_tpu.runner.chaos import InjectedCacheLost
        from sparkdl_tpu.serving import engine as E
        assert failures.classify_exception(
            E.RequestQuarantined("x")) == "fatal"
        assert failures.classify_exception(
            E.EngineStopped("x")) == "retryable"
        assert failures.classify_exception(
            E.DeadlineExceeded("x")) == "fatal"
        assert failures.classify_exception(
            E.RequestCancelled("x")) == "fatal"
        assert failures.classify_exception(
            E.QueueFullError("x")) == "retryable"
        assert failures.classify_exception(
            InjectedCacheLost("injected slot-cache loss")) == "retryable"

        # subclasses inherit via the MRO walk — an ad-hoc subclass of a
        # mapped class needs no entry of its own
        class Custom(E.ServingStallError):
            pass

        assert failures.classify_exception(Custom("y")) == "retryable"
        # text classification (a dead replica's stderr) agrees
        assert failures.classify_text(
            "RequestQuarantined: poisoned request") == "fatal"
        assert failures.classify_text(
            "EngineStopped: engine died") == "retryable"


# ---------------------------------------------------------------------------
# graceful drain + resume (ISSUE 19)
# ---------------------------------------------------------------------------

class TestDrainAndResume:
    def test_drain_returns_resumable_snapshots_token_identical(self):
        """drain() mid-run returns live requests as preemption-resume
        snapshots; feeding them to resume() on a FRESH engine continues
        each stream exactly where it left off — the concatenation is
        bit-identical to an uninterrupted run, nothing re-emitted."""
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=997,
                                           step_s=0.005)).start()
        rs = [eng.submit([i + 1, 5], max_new_tokens=12) for i in range(3)]
        for _ in range(400):  # let some tokens stream first
            if sum(len(r.tokens) for r in rs) >= 4:
                break
            time.sleep(0.005)
        snaps = eng.drain(timeout=5)
        assert snaps, "expected live requests at drain time"
        already = {r.id: list(r.tokens) for r in rs}
        eng2 = GenerationEngine(StubBackend(2, 64, vocab_size=997))
        for s in snaps:
            assert s.state == "queued" and s.slot is None
            eng2.resume(s)
        eng2.run_until_idle()
        clean = GenerationEngine(StubBackend(2, 64, vocab_size=997))
        expect = [clean.submit([i + 1, 5], max_new_tokens=12)
                  for i in range(3)]
        clean.run_until_idle()
        for r, e in zip(rs, expect):
            assert len(r.result(5)) == 12
            assert r.tokens == e.tokens  # identical across the handoff
            assert r.tokens[:len(already[r.id])] == already[r.id]
            assert r.delivered == 12
        with pytest.raises(EngineStopped):
            eng.submit([9], max_new_tokens=2)  # drained engine is closed

    def test_stop_drain_true_shares_drain_path(self):
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100)).start()
        rs = [eng.submit([i + 1], max_new_tokens=3) for i in range(3)]
        out = eng.stop(drain=True, timeout=30)
        assert out == []  # clean drain: everything finished, no snaps
        assert all(r.state == "done" for r in rs)

    def test_overlong_drain_degrades_to_snapshot_and_stop(self):
        """A drain that cannot finish inside its budget (here: a
        workload worth ~50s of decode against a 0.5s timeout) degrades
        to snapshot-and-stop instead of hanging the caller — the
        still-live requests come back as resumable snapshots."""
        eng = GenerationEngine(StubBackend(1, 2048, vocab_size=100,
                                           step_s=0.05)).start()
        r = eng.submit([1], max_new_tokens=1000)
        assert r.wait(0.001) is False
        t0 = time.time()
        snaps = eng.stop(drain=True, timeout=0.5)
        assert time.time() - t0 < 10  # never hung on the drain
        assert any(s is r for s in snaps)
        assert r.state == "queued"  # resumable, not failed


# ---------------------------------------------------------------------------
# stall-free chunked prefill (jax-free scheduler level)
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_decode_interleaves_with_chunked_prefill(self):
        """While a long prompt is consumed chunk by chunk, the already
        RUNNING slot keeps decoding — a decode step lands between every
        pair of chunks (the stall-free point)."""
        be = ChunkRecordingBackend(2, 256, vocab_size=100,
                                   prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=8)
        pump = eng.submit([1], max_new_tokens=40)
        eng.step()  # pump admitted + prefilled + first decode
        long = eng.submit(list(range(2, 66)), max_new_tokens=2)  # 8 chunks
        eng.run_until_idle()
        assert pump.result(1) and long.result(1)
        kinds = [c[0] if c[0] == "step" else f"chunk{c[1]}"
                 for c in be.calls]
        chunk_idx = [i for i, k in enumerate(kinds) if k == "chunk1"]
        assert len(chunk_idx) == 8  # the long request's chunks (slot 1)
        for a, b in zip(chunk_idx, chunk_idx[1:]):
            assert "step" in kinds[a:b], \
                f"no decode step between chunks at {a}..{b}: {kinds}"
        # chunk offsets advance by exactly one chunk per iteration
        assert [c[2] for c in be.calls
                if c[0] == "chunk" and c[1] == 1] == \
            [i * 8 for i in range(8)]

    def test_one_chunk_per_iteration_across_prefilling_slots(self):
        """The per-iteration prefill budget is ONE chunk total (oldest
        admitted first), not one per PREFILLING slot."""
        be = ChunkRecordingBackend(3, 64, vocab_size=100,
                                   prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=4)
        a = eng.submit(list(range(1, 9)), max_new_tokens=1)   # 2 chunks
        b = eng.submit(list(range(11, 19)), max_new_tokens=1)  # 2 chunks
        eng.step()
        assert [c for c in be.calls if c[0] == "chunk"] == \
            [("chunk", 0, 0, 4)]  # one chunk, oldest request, slot 0
        eng.run_until_idle()
        assert a.result(1) and b.result(1)
        # a's chunks complete before b's first chunk runs
        order = [(c[1], c[2]) for c in be.calls if c[0] == "chunk"]
        assert order == [(0, 0), (0, 4), (1, 0), (1, 4)]

    def test_chunk_retry_resumes_from_last_committed_chunk(self):
        """A mid-prompt chunk failure retries THAT chunk — committed
        chunks are never re-run (the cache already holds them)."""
        class FlakyChunk(ChunkRecordingBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.fails = 0

            def prefill_chunk(self, slot, chunk_tokens, offset, n_valid,
                          window=None):
                if offset == 8 and self.fails == 0:
                    self.fails += 1
                    self.calls.append(("boom", slot, offset))
                    raise RuntimeError("transient mid-prompt")
                return super().prefill_chunk(slot, chunk_tokens, offset,
                                             n_valid)

        be = FlakyChunk(1, 64, vocab_size=100, prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=4, retries=1)
        r = eng.submit(list(range(1, 15)), max_new_tokens=2)  # 4 chunks
        eng.run_until_idle()
        assert r.result(1) and r.failures == 1
        offs = [c[2] for c in be.calls if c[0] in ("chunk", "boom")]
        # 0, 4 committed; 8 fails; 8 retried; 12 — never back to 0
        assert offs == [0, 4, 8, 8, 12]
        assert eng.snapshot()["prefill_retries"] == 1

    def test_chunk_retry_exhaustion_quarantines_request_not_gang(self):
        class PoisonChunk(StubBackend):
            def prefill_chunk(self, slot, chunk_tokens, offset, n_valid,
                          window=None):
                if offset == 4:
                    raise RuntimeError("poisoned tail")
                return super().prefill_chunk(slot, chunk_tokens, offset,
                                             n_valid)

        be = PoisonChunk(2, 64, vocab_size=100, prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=4, retries=1)
        good = eng.submit([1, 2], max_new_tokens=4)
        bad = eng.submit(list(range(1, 9)), max_new_tokens=4)  # 2 chunks
        also_good = eng.submit([3], max_new_tokens=4)
        eng.run_until_idle()
        assert good.result(1) and also_good.result(1)
        assert bad.state == "failed" and bad.failures == 2
        with pytest.raises(RequestQuarantined):
            bad.result(1)
        snap = eng.snapshot()
        assert snap["quarantined"] == 1 and snap["completed"] == 2

    def test_prefix_hit_skips_chunks_stream_identical(self):
        be = StubBackend(1, 128, vocab_size=100)  # default cache armed
        eng = GenerationEngine(be, prefill_chunk=4)
        p = list(range(1, 14))  # 13 tokens -> 4 chunks cold
        h1 = eng.submit(p, max_new_tokens=3)
        eng.run_until_idle()
        cold_chunks = eng.snapshot()["prefill_chunks"]
        assert cold_chunks == 4
        h2 = eng.submit(p, max_new_tokens=3)
        eng.run_until_idle()
        snap = eng.snapshot()
        # reuse floor(12/4)*4 = 12 -> tail is 1 token -> ONE chunk
        assert snap["prefill_chunks"] == cold_chunks + 1
        assert h1.result(1) == h2.result(1)
        ps = snap["prefix_cache"]
        assert ps["hits"] == 1 and ps["reused_tokens"] == 12
        # shared head, diverging tail also hits
        h3 = eng.submit(p[:8] + [77, 78], max_new_tokens=3)
        eng.run_until_idle()
        assert eng.snapshot()["prefix_cache"]["hits"] == 2

    def test_prefix_cache_eviction_under_mb_pressure(self):
        # budget fits ~2 of the 3 entries (16 tokens * 1024 B each)
        be = StubBackend(1, 128, vocab_size=100,
                         prefix_cache_bytes=40 * 1024,
                         prefix_bytes_per_token=1024)
        eng = GenerationEngine(be, prefill_chunk=4)
        prompts = [[b + i for i in range(16)] for b in (1, 30, 60)]
        for p in prompts:
            eng.submit(p, max_new_tokens=2)
            eng.run_until_idle()
        ps = eng.snapshot()["prefix_cache"]
        assert ps["evictions"] == 1 and ps["entries"] == 2
        assert ps["bytes"] <= 40 * 1024
        # the evicted (oldest) prompt misses; the resident newest hits
        assert be.begin_prefill(0, prompts[0] + [99], 4) == 0
        assert be.begin_prefill(0, prompts[2] + [99], 4) == 16

    def test_stall_free_env_gate_and_fallback_equivalence(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_SERVE_STALL_FREE", "0")
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        assert eng.stall_free is False
        monkeypatch.delenv("SPARKDL_SERVE_STALL_FREE")
        assert GenerationEngine(
            StubBackend(1, 64, vocab_size=100)).stall_free is True

        def run(stall_free):
            be = RecordingBackend(2, 128, vocab_size=100)
            eng = GenerationEngine(be, prefill_chunk=4,
                                   stall_free=stall_free)
            rs = [eng.submit(list(range(b, b + 9)), max_new_tokens=3)
                  for b in (1, 20, 40, 60)]
            eng.run_until_idle()
            return [r.result(1) for r in rs], be.prefill_log

        toks_sf, log_sf = run(True)
        toks_bl, log_bl = run(False)
        assert toks_sf == toks_bl          # identical streams
        assert log_sf == log_bl            # identical admission order

    def test_blocking_backend_without_chunk_protocol_degrades(self):
        class OldBackend:
            num_slots, max_len, vocab_size = 1, 64, 100

            def __init__(self):
                self._k = 0

            def prefill(self, slot, prompt, bucket):
                self._k = sum(prompt)
                return self._k % 100

            def step(self, active):
                self._k += 1
                return [self._k % 100]

        eng = GenerationEngine(OldBackend())  # wants stall-free...
        assert eng.stall_free is False        # ...degrades to blocking
        r = eng.submit([5, 6], max_new_tokens=3)
        eng.run_until_idle()
        assert len(r.result(1)) == 3

    def test_decode_stall_accounting_blocking_vs_stall_free(self):
        """The acceptance observable at test scale: on a shared-head
        long-prompt mix, the stall-free scheduler (chunks + prefix
        reuse) cuts prefill-induced decode-stall wall time by a wide
        margin vs the blocking path (bench pins the >= 5x on the real
        workload; here >= 2.5x with deterministic synthetic costs)."""
        head = list(range(1, 113))  # 112 shared tokens

        def run(stall_free):
            be = StubBackend(2, 256, vocab_size=200,
                             prefill_tok_s=0.0002,
                             prefix_bytes_per_token=64)
            eng = GenerationEngine(be, prefill_chunk=16,
                                   stall_free=stall_free, min_bucket=16)
            pump = eng.submit([199], max_new_tokens=3)
            eng.run_until_idle()  # slot 0 free again; stats keep
            pump2 = eng.submit([198], max_new_tokens=200)  # stays RUNNING
            for i in range(8):
                eng.submit(head + [150 + i for _ in range(8)],
                           max_new_tokens=1)
            eng.run_until_idle()
            assert pump2.result(1)
            return eng.snapshot()

        sf = run(True)
        bl = run(False)
        assert bl["decode_stall_s"] > 0 and sf["decode_stall_s"] > 0
        ratio = bl["decode_stall_s"] / sf["decode_stall_s"]
        assert ratio >= 2.5, (bl["decode_stall_s"], sf["decode_stall_s"])
        # stall EVENTS: blocking = one per whole prefill; stall-free =
        # one per chunk that ran while a RUNNING slot waited
        assert sf["decode_stall_events"] >= bl["decode_stall_events"]

    def test_stall_metrics_reach_telemetry_and_recorder(self):
        from sparkdl_tpu.runner import events
        telemetry.reset()
        telemetry.start()
        rec = events.reset()
        try:
            be = StubBackend(2, 64, vocab_size=100, prefix_cache_bytes=0)
            eng = GenerationEngine(be, prefill_chunk=4)
            eng.submit([1], max_new_tokens=20)
            eng.step()  # running
            eng.submit(list(range(2, 10)), max_new_tokens=1)
            eng.run_until_idle()
            snap = telemetry.registry().snapshot()
            assert snap["counters"]["serving_decode_stall_s_total"] > 0
            hist = snap["histograms"]["serve_decode_stall_s"]
            assert hist["count"] == eng.snapshot()["decode_stall_events"]
            names = [e["name"] for e in rec.ring
                     if e.get("ph") == "E" or e.get("dur_s") is not None]
            assert "serve_decode_stall" in names
        finally:
            telemetry.reset()
            events.reset()


class TestPrefixCacheUnit:
    def test_common_prefix_lookup_and_counters(self):
        from sparkdl_tpu.serving import PrefixCache
        pc = PrefixCache(10_000)
        assert pc.lookup([1, 2, 3]) == (None, 0, None)
        pc.put([1, 2, 3, 4], "payloadA", 100)
        key, shared, payload = pc.lookup([1, 2, 3, 4, 5, 6])
        assert shared == 4 and payload == "payloadA"
        # diverging tail: only the common head counts
        _, shared2, _ = pc.lookup([1, 2, 9, 9])
        assert shared2 == 2
        pc.use(key, 4)
        pc.note_miss()
        st = pc.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["reused_tokens"] == 4 and st["hit_rate"] == 0.5

    def test_lru_eviction_order_and_budget(self):
        from sparkdl_tpu.serving import PrefixCache
        pc = PrefixCache(250)
        pc.put([1], "a", 100)
        pc.put([2], "b", 100)
        key, _, _ = pc.lookup([1, 5])
        pc.use(key, 1)          # touch "a" -> "b" is now LRU
        pc.put([3], "c", 100)   # evicts "b"
        assert pc.lookup([2, 5])[2] is None
        assert pc.lookup([1, 5])[2] == "a"
        assert pc.stats()["evictions"] == 1
        # an entry over the whole budget is refused, not crashed
        assert pc.put([9], "huge", 999) is False
        assert pc.stats()["oversize"] == 1
        # re-putting an existing key refreshes, never double-counts
        assert pc.put([1], "a2", 100) is True
        assert pc.stats()["bytes"] == 200 and pc.lookup([1])[2] == "a"


# ---------------------------------------------------------------------------
# telemetry plumbing (jax-free)
# ---------------------------------------------------------------------------

class TestServingTelemetry:
    def test_histogram_quantile_math(self):
        h = {"bounds": [1.0, 2.0, 4.0], "buckets": [2, 6, 8],
             "count": 8, "sum": 0.0}
        # rank p50 = 4 -> second bucket, interp (4-2)/(6-2) of [1,2]
        assert telemetry.histogram_quantile(h, 0.5) == pytest.approx(1.5)
        assert telemetry.histogram_quantile(h, 0.0) == pytest.approx(0.0)
        assert telemetry.histogram_quantile(h, 1.0) == pytest.approx(4.0)
        # rank past the last finite bound clamps to it
        h2 = {"bounds": [1.0], "buckets": [1], "count": 10, "sum": 0.0}
        assert telemetry.histogram_quantile(h2, 0.99) == 1.0
        assert telemetry.histogram_quantile(
            {"bounds": [], "buckets": [], "count": 0}, 0.5) is None
        # the live-histogram method rides the same derivation
        hist = telemetry.Histogram(buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 1.7):
            hist.observe(v)
        assert hist.quantile(1.0) == pytest.approx(2.0)

    def test_aggregate_snapshots_merges_histograms(self, tmp_path):
        import json
        snap = {"t": 1.0, "elapsed_s": 1.0, "stages": {},
                "histograms": {"serving_request_latency_s": {
                    "bounds": [1.0, 2.0], "buckets": [1, 2],
                    "count": 2, "sum": 2.5}}}
        for rank in (0, 1):
            (tmp_path / f"metrics_rank{rank}.json").write_text(
                json.dumps(dict(snap, rank=rank)))
        agg = telemetry.aggregate_snapshots(str(tmp_path))
        h = agg["histograms"]["serving_request_latency_s"]
        assert h["buckets"] == [2, 4] and h["count"] == 4
        assert h["sum"] == pytest.approx(5.0)
        assert telemetry.histogram_quantile(h, 0.5) is not None

    def test_engine_metrics_when_plane_armed(self):
        telemetry.reset()
        telemetry.start()
        try:
            eng = GenerationEngine(StubBackend(2, 64, vocab_size=100))
            rs = [eng.submit([i + 1], max_new_tokens=3) for i in range(4)]
            eng.run_until_idle()
            assert all(r.state == "done" for r in rs)
            snap = telemetry.registry().snapshot()
            assert snap["counters"]["serving_tokens_total"] == 12
            assert snap["counters"][
                "serving_requests_completed_total"] == 4
            assert snap["gauges"]["serving_queue_depth"]["max"] >= 1
            assert snap["gauges"]["serving_slots_busy"]["max"] == 2
            lat = snap["histograms"]["serving_request_latency_s"]
            assert lat["count"] == 4
            assert telemetry.histogram_quantile(lat, 0.5) is not None
            assert snap["histograms"]["serving_ttft_s"]["count"] == 4
        finally:
            telemetry.reset()

    def test_engine_registers_nothing_when_plane_off(self):
        telemetry.reset()
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()
        assert telemetry.registry().snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}

    def test_request_spans_reach_flight_recorder(self):
        from sparkdl_tpu.runner import events
        rec = events.reset()
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        r = eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()
        names = [e["name"] for e in rec.ring]
        for span in ("serve_queue", "serve_prefill", "serve_decode"):
            assert f"{span}" in names, names
        ends = [e for e in rec.ring
                if e["ph"] == "E" and e["name"] == "serve_decode"]
        assert ends and ends[0]["request"] == r.id
        assert ends[0]["rows"] == 2


# ---------------------------------------------------------------------------
# engine on CPU over the tiny model (lean: one compile set, one test)
# ---------------------------------------------------------------------------

class TestEngineOnCpu:
    def test_token_identical_fast_twin(self):
        """Lean twin of the slow staggered-refill test (ISSUE 11 tier-1
        buy-back): TWO same-bucket prompts through a 2-slot blocking
        engine — one prefill program, one decode program, one static
        reference compile — pinning the engine-vs-generate() identity
        contract in a fraction of the wall time. The 4-prompt
        mixed-bucket + EOS variant runs behind ``slow``."""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(3)
        max_len = 32
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 7)]  # one bucket (8)
        ids, lens = L.left_pad_prompts(prompts, pad_to=8)
        out = np.asarray(L.generate(model, variables, np.asarray(ids), 4,
                                    pad_lens=np.asarray(lens),
                                    pad_to=max_len))
        refs = [out[i][int(lens[i]) + len(p):].tolist()
                for i, p in enumerate(prompts)]
        eng = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len, min_bucket=8,
            stall_free=False)
        handles = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run_until_idle()
        assert eng.snapshot()["peak_slots_busy"] == 2
        for h, want in zip(handles, refs):
            assert h.result(1) == want

    @pytest.mark.slow
    def test_token_identical_with_staggered_refill_and_eos(self):
        """Mixed-length requests through a 2-slot engine emit exactly
        the static generate() greedy tokens — including a request
        refilled mid-decode into a retired slot (different bucket), and
        EOS retirement behaving like generate()'s while_loop."""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 2, 9, 3)]  # buckets 8 and 16
        max_len = 64

        def ref(prompt, new, eos=None):
            ids, lens = L.left_pad_prompts([prompt])
            out = L.generate(model, variables, np.asarray(ids), new,
                             pad_lens=np.asarray(lens), pad_to=max_len,
                             eos_id=eos)
            row = np.asarray(out)[0][int(lens[0]) + len(prompt):]
            toks = row.tolist()
            if eos is not None and eos in toks:
                toks = toks[:toks.index(eos) + 1]
            return toks

        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=max_len, min_bucket=8)
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["peak_slots_busy"] == 2  # genuinely in-flight
        for p, h in zip(prompts, handles):
            assert h.result(1) == ref(p, 6), p

        # EOS: pick a token the greedy stream emits mid-sequence, serve
        # with it as eos_id — engine must stop exactly where the static
        # while_loop path stops.
        stream = ref(prompts[0], 6)
        eos = next((t for i, t in enumerate(stream) if 0 < i < 5), None)
        if eos is not None:
            eng2 = GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=max_len,
                min_bucket=8, eos_id=int(eos))
            h = eng2.submit(prompts[0], max_new_tokens=6)
            eng2.run_until_idle()
            assert h.result(1) == ref(prompts[0], 6, eos=int(eos))
            assert h.finish_reason in ("eos", "length")

    def test_chunked_prefill_identity_fast_twin(self):
        """Lean twin of the slow 4-prompt chunked-identity test (ISSUE
        12 tier-1 buy-back, the PR 8/9/11 pattern): ONE 1-chunk and ONE
        2-chunk prompt through a 2-slot chunked engine — same
        engine-vs-generate() identity contract and zero-decode-re-trace
        pin, a fraction of the compile set. The 3-chunk + prefix-reuse
        composition runs behind ``slow`` (and the speculative variant
        of the same composition runs fast in tests/test_spec.py)."""
        import jax

        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(5)
        max_len = 64
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 9)]  # 1 and 2 chunks
        ids, lens = L.left_pad_prompts(prompts)
        out = np.asarray(L.generate(model, variables, np.asarray(ids), 6,
                                    pad_lens=np.asarray(lens),
                                    pad_to=max_len))
        refs = [out[i][int(lens[i]) + len(p):].tolist()
                for i, p in enumerate(prompts)]
        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=max_len, prefill_chunk=8)
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["peak_slots_busy"] == 2
        assert snap["prefill_chunks"] == 1 + 2
        for h, want in zip(handles, refs):
            assert h.result(1) == want
        # ONE decode program for this engine (first compile of its
        # (slots, max_len) shape at most) — the staggered 1- and
        # 2-chunk refills never re-trace it
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") - sig_decode <= 1

    @pytest.mark.slow
    def test_chunked_prefill_token_identity_and_prefix_reuse(self):
        """Chunk size 8 over prompts of 3/5/9/17 tokens: refills prefill
        in 1, 2 and 3 chunks, staggered across 2 slots while neighbors
        decode — greedy output must equal static generate() exactly;
        then shared-head prompts ride prefix-cache hits and must STILL
        be token-identical, with zero decode re-traces throughout."""
        import jax

        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(5)
        max_len = 64

        def ref(prompt, new):
            ids, lens = L.left_pad_prompts([prompt])
            out = L.generate(model, variables, np.asarray(ids), new,
                             pad_lens=np.asarray(lens), pad_to=max_len)
            return np.asarray(out)[0][int(lens[0]) + len(prompt):].tolist()

        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 9, 17, 3)]  # 1 / 2 / 3 / 1 chunks
        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=max_len, prefill_chunk=8)
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["peak_slots_busy"] == 2  # genuinely in-flight
        assert snap["prefill_chunks"] == 1 + 2 + 3 + 1
        for p, h in zip(prompts, handles):
            assert h.result(1) == ref(p, 6), len(p)
        sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

        # shared 12-token head, diverging tails -> prefix hits; output
        # must stay bit-equal to a cold static run
        head = rng.randint(0, cfg.vocab_size, 12).tolist()
        pa = head + rng.randint(0, cfg.vocab_size, 4).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 7).tolist()
        ha = eng.submit(pa, max_new_tokens=5)
        eng.run_until_idle()  # pa commits its rows before pb looks up
        hb = eng.submit(pb, max_new_tokens=5)
        eng.run_until_idle()
        assert ha.result(1) == ref(pa, 5) and hb.result(1) == ref(pb, 5)
        ps = eng.snapshot()["prefix_cache"]
        assert ps["hits"] >= 1 and ps["reused_tokens"] >= 8
        # refills + prefix scatters never re-trace the decode step
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") == sig_decode

    def test_prefix_hit_kv_bit_identical_and_blocking_fallback(self):
        """A prefix-cache hit must leave the slot's K/V rows BIT
        IDENTICAL to a cold chunked prefill of the same prompt (same
        engine config, prefix cache disabled); the blocking fallback
        path must emit the same greedy tokens as the static path."""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(11)
        max_len = 64
        head = rng.randint(0, cfg.vocab_size, 16).tolist()
        seed_p = head + rng.randint(0, cfg.vocab_size, 4).tolist()
        p2 = head + rng.randint(0, cfg.vocab_size, 6).tolist()

        def make(prefix_mb):
            return GenerationEngine.from_model(
                model, variables, num_slots=1, max_len=max_len,
                prefill_chunk=8, prefix_cache_mb=prefix_mb)

        eng_hit, eng_cold = make(None), make(0)
        h = eng_hit.submit(seed_p, max_new_tokens=2)
        eng_hit.run_until_idle()
        assert h.result(1)  # head committed to the prefix cache
        outs = []
        for eng in (eng_hit, eng_cold):
            h2 = eng.submit(p2, max_new_tokens=3)
            eng.run_until_idle()
            outs.append(h2.result(1))
        assert outs[0] == outs[1]
        assert eng_hit.snapshot()["prefix_cache"]["hits"] == 1
        assert "prefix_cache" not in eng_cold.snapshot()
        # K/V rows of the written region: bit identical hit vs cold
        n_rows = len(p2) + 3
        for a, b in zip(
                jax.tree_util.tree_leaves(eng_hit.backend.cache),
                jax.tree_util.tree_leaves(eng_cold.backend.cache)):
            if getattr(a, "ndim", 0) != 4:
                continue
            assert np.array_equal(np.asarray(a)[0, :, :n_rows],
                                  np.asarray(b)[0, :, :n_rows])

        # blocking fallback: same tokens as the static reference
        eng_bl = GenerationEngine.from_model(
            model, variables, num_slots=1, max_len=max_len,
            stall_free=False)
        assert eng_bl.stall_free is False
        hb = eng_bl.submit(p2, max_new_tokens=3)
        eng_bl.run_until_idle()
        assert hb.result(1) == outs[0]

@pytest.mark.slow
def test_serve_smoke_end_to_end():
    """Concurrent submitters, no starvation, aggregate > single-stream,
    zero decode re-traces (scripts/serve_smoke.py, in-process)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "serve_smoke", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "serve_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


@pytest.mark.slow
def test_serve_chaos_smoke_end_to_end():
    """ISSUE 19 survivability evidence: injected cache_lost at
    serve_decode + serve_alloc across Stub/Llama x unpaged/paged,
    token-identical failover with a zero-dup/zero-loss stream ledger,
    the budget counterfactual failing closed classified, drain/resume
    identity, and the three-way quarantine ledger agreement
    (scripts/serve_chaos_smoke.py, in-process)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_chaos_smoke", os.path.join(
            os.path.dirname(__file__), "..", "scripts",
            "serve_chaos_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


@pytest.mark.slow
def test_fleet_chaos_smoke_end_to_end():
    """ISSUE 20 fleet evidence: a ≥3-replica fleet surviving one
    injected unclean replica_dead AND one DOOMED drain-and-re-admit
    per backend shape (Stub/Llama x unpaged/paged), token-identical to
    a clean single-engine run with a zero-dup/zero-loss delivery-cursor
    audit; the SPARKDL_FLEET_MIN_REPLICAS counterfactual failing closed
    classified; and the radix-aware router beating round-robin on
    fleet-wide prefix reuse (scripts/fleet_chaos_smoke.py,
    in-process)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleet_chaos_smoke", os.path.join(
            os.path.dirname(__file__), "..", "scripts",
            "fleet_chaos_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
