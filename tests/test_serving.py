"""Continuous-batching serving engine (ISSUE 8).

Three layers, leanest first: jax-free scheduler unit tests over
scripted backends (refill ordering, admission control, EOS retirement,
streaming callback order, per-request quarantine, stall watchdog),
device-free telemetry plumbing (histogram quantiles + gang
aggregation), then ONE engine-on-CPU equivalence test over
``LlamaConfig.tiny`` (slot prefill/decode + staggered refill must be
token-identical to the static ``generate()`` path) and the slow
serve-smoke e2e.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.runner import telemetry
from sparkdl_tpu.serving import (EngineStopped, GenerationEngine,
                                 QueueFullError, RequestQuarantined,
                                 RequestRejected, ServingStallError,
                                 StubBackend, bucket_length)


class RecordingBackend(StubBackend):
    """Stub that records the (prompt, slot) order of every prefill —
    the scheduler-ordering observable."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.prefill_log: list[tuple[tuple, int]] = []

    def prefill(self, slot, prompt, bucket):
        self.prefill_log.append((tuple(prompt), slot))
        return super().prefill(slot, prompt, bucket)


# ---------------------------------------------------------------------------
# jax-free scheduler unit tests
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_fifo_refill_order_lowest_slot_first(self):
        be = RecordingBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        reqs = [eng.submit([i, i + 1], max_new_tokens=3) for i in range(5)]
        eng.run_until_idle()
        # admitted strictly in submission order
        assert [p for p, _ in be.prefill_log] == \
            [tuple(r.prompt) for r in reqs]
        # first two land on slots 0 and 1 (lowest free slot first)
        assert [s for _, s in be.prefill_log[:2]] == [0, 1]
        for r in reqs:
            assert r.result(1) and r.finish_reason == "length"
        assert eng.snapshot()["completed"] == 5

    def test_requests_overlap_across_slots(self):
        """A freed slot refills while the other slot's request is still
        decoding — the batch never drains."""
        be = StubBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        long = eng.submit([1], max_new_tokens=12)
        short = eng.submit([2], max_new_tokens=2)
        third = eng.submit([3], max_new_tokens=2)
        eng.run_until_idle()
        # third was admitted into short's freed slot BEFORE long retired
        assert third.t_admit < long.t_done
        assert eng.snapshot()["peak_slots_busy"] == 2
        assert all(r.state == "done" for r in (long, short, third))

    def test_stream_callback_order_first_token_included(self):
        per_req: dict = {}
        be = StubBackend(2, 64, vocab_size=100)
        eng = GenerationEngine(be)
        reqs = [eng.submit([i + 1, 7], max_new_tokens=4,
                           stream_cb=lambda r, t:
                           per_req.setdefault(r.id, []).append(t))
                for i in range(3)]
        eng.run_until_idle()
        for r in reqs:
            assert per_req[r.id] == r.result(1)  # every token, in order
            assert len(per_req[r.id]) == 4

    def test_broken_callback_never_kills_the_loop(self):
        def boom(r, t):
            raise RuntimeError("client bug")
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        r = eng.submit([1], max_new_tokens=3, stream_cb=boom)
        eng.run_until_idle()
        assert r.result(1) and eng.snapshot()["callback_errors"] == 3

    def test_eos_retires_slot_early(self):
        class EosAt2(StubBackend):
            def _tok(self, key, n):
                return 9 if n == 2 else (key + n) % self.vocab_size

        eng = GenerationEngine(EosAt2(1, 64, vocab_size=100), eos_id=9)
        r = eng.submit([5], max_new_tokens=40)
        eng.run_until_idle()
        out = r.result(1)
        assert out[-1] == 9 and len(out) == 3  # eos included, then stop
        assert r.finish_reason == "eos"

    def test_admission_rejects(self):
        eng = GenerationEngine(StubBackend(2, 64, vocab_size=100),
                               min_bucket=8)
        with pytest.raises(RequestRejected, match="empty"):
            eng.submit([], max_new_tokens=4)
        with pytest.raises(RequestRejected, match="outside vocab"):
            eng.submit([5, 100], max_new_tokens=4)
        with pytest.raises(RequestRejected, match="exceeds max_len"):
            eng.submit(list(range(1, 40)), max_new_tokens=32)  # 64+32>64
        with pytest.raises(RequestRejected, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)
        assert eng.snapshot()["rejected"] == 4

    def test_queue_backpressure(self):
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100),
                               queue_capacity=1)
        eng.submit([1], max_new_tokens=2)
        with pytest.raises(QueueFullError):
            eng.submit([2], max_new_tokens=2, block=False)
        with pytest.raises(QueueFullError):
            eng.submit([2], max_new_tokens=2, timeout=0.05)
        snap = eng.snapshot()
        assert snap["rejected"] == 2 and snap["queue_depth"] == 1
        eng.run_until_idle()
        # space freed -> accepted again
        assert eng.submit([3], max_new_tokens=2, block=False)
        eng.run_until_idle()

    def test_prefill_retry_then_success(self):
        class FlakyOnce(StubBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.fails = 0

            def prefill(self, slot, prompt, bucket):
                if prompt[0] == 42 and self.fails == 0:
                    self.fails += 1
                    raise RuntimeError("transient")
                return super().prefill(slot, prompt, bucket)

        eng = GenerationEngine(FlakyOnce(1, 64, vocab_size=100), retries=1)
        r = eng.submit([42], max_new_tokens=3)
        eng.run_until_idle()
        assert r.result(1) and r.failures == 1
        assert eng.snapshot()["prefill_retries"] == 1

    def test_prefill_quarantine_after_repeated_failure(self):
        class Poison(StubBackend):
            def prefill(self, slot, prompt, bucket):
                if prompt[0] == 99:
                    raise RuntimeError("bad prompt payload")
                return super().prefill(slot, prompt, bucket)

        eng = GenerationEngine(Poison(2, 64, vocab_size=100), retries=2)
        good = eng.submit([1, 2], max_new_tokens=4)
        bad = eng.submit([99], max_new_tokens=4)
        also_good = eng.submit([3], max_new_tokens=4)
        eng.run_until_idle()
        # the poisoned request is evicted, not the gang
        assert good.result(1) and also_good.result(1)
        assert bad.state == "failed" and bad.failures == 3
        with pytest.raises(RequestQuarantined):
            bad.result(1)
        snap = eng.snapshot()
        assert snap["quarantined"] == 1 and snap["completed"] == 2

    def test_step_failure_evicts_newest_suspect(self):
        class StepPoison(StubBackend):
            def step(self, active):
                # key = sum(prompt) + len(prompt); [99] -> 100
                if any(self._state[s][0] == 100 for s in active):
                    raise RuntimeError("poisoned decode")
                return super().step(active)

        eng = GenerationEngine(StepPoison(2, 64, vocab_size=200),
                               retries=1)
        survivor = eng.submit([1, 2], max_new_tokens=6)
        poison = eng.submit([99], max_new_tokens=6)
        eng.run_until_idle()
        assert survivor.result(1) and survivor.finish_reason == "length"
        assert poison.state == "failed"
        snap = eng.snapshot()
        assert snap["quarantined"] == 1 and snap["step_retries"] >= 1

    def test_sole_occupant_eviction_keeps_engine_alive(self):
        """A poisoned request that is the ONLY one in flight is evicted
        exactly like a co-resident one — the engine survives and keeps
        serving the queue (eviction must never be gang-fatal)."""
        class StepPoison(StubBackend):
            def step(self, active):
                if any(self._state[s][0] == 100 for s in active):  # [99]
                    raise RuntimeError("poisoned decode")
                return super().step(active)

        eng = GenerationEngine(StepPoison(2, 64, vocab_size=200),
                               retries=1)
        poison = eng.submit([99], max_new_tokens=6)  # alone in flight
        eng.run_until_idle()
        assert poison.state == "failed"
        assert eng.snapshot()["quarantined"] == 1
        # engine alive: a new request completes normally
        after = eng.submit([1, 2], max_new_tokens=4)
        eng.run_until_idle()
        assert after.result(1) and after.finish_reason == "length"

    def test_serving_fatal_error_skips_retry_and_fails_over(self):
        """An error flagged ``serving_fatal`` (backend.SlotCacheLost:
        the donated cache was consumed — retrying would read a deleted
        buffer) must fail the engine over immediately: no retry burned,
        no innocent requests evicted one by one."""
        class CacheGone(RuntimeError):
            serving_fatal = True

        class LostCache(StubBackend):
            def step(self, active):
                raise CacheGone("cache consumed mid-execution")

        eng = GenerationEngine(LostCache(2, 64, vocab_size=100),
                               retries=3)
        a = eng.submit([1], max_new_tokens=5)
        b = eng.submit([2], max_new_tokens=5)
        with pytest.raises(CacheGone):
            eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["step_retries"] == 0 and snap["quarantined"] == 0
        for r in (a, b):
            assert r.state == "failed" and \
                isinstance(r.error, EngineStopped)
        with pytest.raises(EngineStopped):
            eng.submit([3], max_new_tokens=2)

    def test_stall_watchdog_names_stage_and_fails_pending(self):
        class Wedged(StubBackend):
            def step(self, active):
                time.sleep(3)
                return super().step(active)

        eng = GenerationEngine(Wedged(1, 64, vocab_size=100), stall_s=0.2)
        r = eng.submit([1], max_new_tokens=5)
        with pytest.raises(ServingStallError, match="decode_step"):
            eng.run_until_idle()
        assert r.state == "failed" and isinstance(r.error, EngineStopped)

    def test_stop_now_fails_pending_drain_completes(self):
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100,
                                           step_s=0.002)).start()
        rs = [eng.submit([i + 1], max_new_tokens=4) for i in range(4)]
        eng.stop(drain=True, timeout=30)
        assert all(r.state == "done" for r in rs)
        eng2 = GenerationEngine(StubBackend(1, 64, vocab_size=100,
                                            step_s=0.05)).start()
        rs2 = [eng2.submit([i + 1], max_new_tokens=40) for i in range(3)]
        eng2.stop(drain=False, timeout=30)
        assert any(r.state == "failed" and
                   isinstance(r.error, EngineStopped) for r in rs2)
        with pytest.raises(EngineStopped):
            eng2.submit([9], max_new_tokens=2)

    def test_concurrent_submitters_all_complete(self):
        eng = GenerationEngine(StubBackend(4, 64, vocab_size=100),
                               queue_capacity=8).start()
        handles, hlock = [], threading.Lock()

        def client(base):
            for i in range(6):
                h = eng.submit([base, i + 1], max_new_tokens=3)
                with hlock:
                    handles.append(h)
                h.result(timeout=30)

        threads = [threading.Thread(target=client, args=(b,))
                   for b in (1, 2, 3, 4, 5, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        eng.stop(drain=True, timeout=30)
        assert len(handles) == 36
        assert all(h.state == "done" for h in handles)  # nothing starves

    def test_queue_capacity_floor(self):
        # capacity 0 would make every blocking submit spin forever
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100),
                               queue_capacity=0)
        assert eng.queue_capacity == 1
        assert eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()

    def test_bucket_length_contract(self):
        assert bucket_length(1, 8) == 8
        assert bucket_length(8, 8) == 8
        assert bucket_length(9, 8) == 16
        assert bucket_length(33, 8) == 64
        with pytest.raises(ValueError):
            bucket_length(0, 8)


# ---------------------------------------------------------------------------
# telemetry plumbing (jax-free)
# ---------------------------------------------------------------------------

class TestServingTelemetry:
    def test_histogram_quantile_math(self):
        h = {"bounds": [1.0, 2.0, 4.0], "buckets": [2, 6, 8],
             "count": 8, "sum": 0.0}
        # rank p50 = 4 -> second bucket, interp (4-2)/(6-2) of [1,2]
        assert telemetry.histogram_quantile(h, 0.5) == pytest.approx(1.5)
        assert telemetry.histogram_quantile(h, 0.0) == pytest.approx(0.0)
        assert telemetry.histogram_quantile(h, 1.0) == pytest.approx(4.0)
        # rank past the last finite bound clamps to it
        h2 = {"bounds": [1.0], "buckets": [1], "count": 10, "sum": 0.0}
        assert telemetry.histogram_quantile(h2, 0.99) == 1.0
        assert telemetry.histogram_quantile(
            {"bounds": [], "buckets": [], "count": 0}, 0.5) is None
        # the live-histogram method rides the same derivation
        hist = telemetry.Histogram(buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 1.7):
            hist.observe(v)
        assert hist.quantile(1.0) == pytest.approx(2.0)

    def test_aggregate_snapshots_merges_histograms(self, tmp_path):
        import json
        snap = {"t": 1.0, "elapsed_s": 1.0, "stages": {},
                "histograms": {"serving_request_latency_s": {
                    "bounds": [1.0, 2.0], "buckets": [1, 2],
                    "count": 2, "sum": 2.5}}}
        for rank in (0, 1):
            (tmp_path / f"metrics_rank{rank}.json").write_text(
                json.dumps(dict(snap, rank=rank)))
        agg = telemetry.aggregate_snapshots(str(tmp_path))
        h = agg["histograms"]["serving_request_latency_s"]
        assert h["buckets"] == [2, 4] and h["count"] == 4
        assert h["sum"] == pytest.approx(5.0)
        assert telemetry.histogram_quantile(h, 0.5) is not None

    def test_engine_metrics_when_plane_armed(self):
        telemetry.reset()
        telemetry.start()
        try:
            eng = GenerationEngine(StubBackend(2, 64, vocab_size=100))
            rs = [eng.submit([i + 1], max_new_tokens=3) for i in range(4)]
            eng.run_until_idle()
            assert all(r.state == "done" for r in rs)
            snap = telemetry.registry().snapshot()
            assert snap["counters"]["serving_tokens_total"] == 12
            assert snap["counters"][
                "serving_requests_completed_total"] == 4
            assert snap["gauges"]["serving_queue_depth"]["max"] >= 1
            assert snap["gauges"]["serving_slots_busy"]["max"] == 2
            lat = snap["histograms"]["serving_request_latency_s"]
            assert lat["count"] == 4
            assert telemetry.histogram_quantile(lat, 0.5) is not None
            assert snap["histograms"]["serving_ttft_s"]["count"] == 4
        finally:
            telemetry.reset()

    def test_engine_registers_nothing_when_plane_off(self):
        telemetry.reset()
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()
        assert telemetry.registry().snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}

    def test_request_spans_reach_flight_recorder(self):
        from sparkdl_tpu.runner import events
        rec = events.reset()
        eng = GenerationEngine(StubBackend(1, 64, vocab_size=100))
        r = eng.submit([1], max_new_tokens=2)
        eng.run_until_idle()
        names = [e["name"] for e in rec.ring]
        for span in ("serve_queue", "serve_prefill", "serve_decode"):
            assert f"{span}" in names, names
        ends = [e for e in rec.ring
                if e["ph"] == "E" and e["name"] == "serve_decode"]
        assert ends and ends[0]["request"] == r.id
        assert ends[0]["rows"] == 2


# ---------------------------------------------------------------------------
# engine on CPU over the tiny model (lean: one compile set, one test)
# ---------------------------------------------------------------------------

class TestEngineOnCpu:
    def test_token_identical_with_staggered_refill_and_eos(self):
        """Mixed-length requests through a 2-slot engine emit exactly
        the static generate() greedy tokens — including a request
        refilled mid-decode into a retired slot (different bucket), and
        EOS retirement behaving like generate()'s while_loop."""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 2, 9, 3)]  # buckets 8 and 16
        max_len = 64

        def ref(prompt, new, eos=None):
            ids, lens = L.left_pad_prompts([prompt])
            out = L.generate(model, variables, np.asarray(ids), new,
                             pad_lens=np.asarray(lens), pad_to=max_len,
                             eos_id=eos)
            row = np.asarray(out)[0][int(lens[0]) + len(prompt):]
            toks = row.tolist()
            if eos is not None and eos in toks:
                toks = toks[:toks.index(eos) + 1]
            return toks

        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=max_len, min_bucket=8)
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["peak_slots_busy"] == 2  # genuinely in-flight
        for p, h in zip(prompts, handles):
            assert h.result(1) == ref(p, 6), p

        # EOS: pick a token the greedy stream emits mid-sequence, serve
        # with it as eos_id — engine must stop exactly where the static
        # while_loop path stops.
        stream = ref(prompts[0], 6)
        eos = next((t for i, t in enumerate(stream) if 0 < i < 5), None)
        if eos is not None:
            eng2 = GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=max_len,
                min_bucket=8, eos_id=int(eos))
            h = eng2.submit(prompts[0], max_new_tokens=6)
            eng2.run_until_idle()
            assert h.result(1) == ref(prompts[0], 6, eos=int(eos))
            assert h.finish_reason in ("eos", "length")


@pytest.mark.slow
def test_serve_smoke_end_to_end():
    """Concurrent submitters, no starvation, aggregate > single-stream,
    zero decode re-traces (scripts/serve_smoke.py, in-process)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "serve_smoke", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "serve_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
