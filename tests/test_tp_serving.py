"""Tensor-parallel serving (ISSUE 14): the engine spanning a
``Mesh(('tp',))`` with head-sharded weights + KV cache/pool.

Fast tier: SpecLayout/divisibility units, the launcher's topology-aware
placement (jax-free), the tp-mesh offset contract, the tp=1
exact-existing-path pin (types + compile-cache signature equality), and
ONE lean tp=2 composition identity test (paging + radix graft + chunked
prefill + speculation + preemption-resume vs static ``generate()``,
per-device KV bytes at 1/2, zero re-traces, tp gauges). The full
degree × layout matrix runs behind ``slow``.

The suite rides the conftest-forced 8-virtual-device CPU mesh — the
same surface the driver's multichip dryrun validates on.
"""

import numpy as np
import pytest

from sparkdl_tpu.runner.launcher import _tp_degree, tp_placement_env
from sparkdl_tpu.serving import GenerationEngine


def _tiny_model():
    """LlamaConfig.tiny(): num_kv_heads=2 — exact head split at tp=2."""
    import jax

    from sparkdl_tpu.models import llama as L
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    return cfg, model, variables


def _tp4_model():
    """num_kv_heads=4 — exact head split at every degree in {1,2,4}."""
    import jax

    from sparkdl_tpu.models import llama as L
    cfg = L.LlamaConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, num_kv_heads=4,
                        intermediate_size=256, rope_theta=10000.0)
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(1),
                           np.zeros((1, 4), np.int32))
    return cfg, model, variables


def _static_refs(model, variables, prompts, new, max_len=64):
    from sparkdl_tpu.models import llama as L
    ids, lens = L.left_pad_prompts(prompts)
    out = np.asarray(L.generate(model, variables, np.asarray(ids), new,
                                pad_lens=np.asarray(lens),
                                pad_to=max_len))
    return [out[i][int(lens[i]) + len(p):].tolist()
            for i, p in enumerate(prompts)]


def _global_kv_bytes(cache):
    import jax
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(cache)
               if getattr(x, "ndim", 0) == 4)


class TestSpecLayout:
    def test_layout_fields_and_head_validation(self):
        from sparkdl_tpu.parallel import serving_tp_layout
        lay = serving_tp_layout(2)
        assert lay.degree == 2 and lay.axis == "tp"
        assert tuple(lay.kv_cache) == (None, "tp", None, None)
        assert tuple(lay.replicated) == ()

        class C:
            num_kv_heads = 2
            num_heads = 4

        serving_tp_layout(2, C)  # exact split: fine
        serving_tp_layout(1, C)  # degenerate: always fine
        with pytest.raises(ValueError, match="num_kv_heads"):
            serving_tp_layout(4, C)
        with pytest.raises(ValueError, match="tp must be >= 1"):
            serving_tp_layout(0)

    def test_divisible_rules_drop_uneven_axes(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        from sparkdl_tpu.parallel import divisible_rules, make_rules
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        base = make_rules([(r"odd_vocab", P(None, "tp")),
                           (r"kernel", P(None, "tp"))])
        rules = divisible_rules(base, mesh)
        # 5 % 2 != 0: the tp axis is dropped (replicated), not an error
        assert rules(("odd_vocab",), np.zeros((4, 5))) == P(None, None)
        assert rules(("kernel",), np.zeros((4, 6))) == P(None, "tp")
        # non-matching leaves keep the empty default untouched
        assert rules(("bias",), np.zeros((3,))) == P()


class TestTpPlacement:
    """Launcher topology-aware placement — jax-free policy units."""

    def test_tp1_adds_nothing(self):
        assert tp_placement_env(0, 1, {"JAX_PLATFORMS": "cpu"}) == {}

    def test_cpu_forces_per_rank_virtual_devices(self):
        add = tp_placement_env(2, 4, {"JAX_PLATFORMS": "cpu"})
        assert "--xla_force_host_platform_device_count=4" in \
            add["XLA_FLAGS"]
        assert add["SPARKDL_TP_DEVICE_OFFSET"] == "0"

    def test_cpu_respects_caller_pinned_flag(self):
        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=16"}
        add = tp_placement_env(0, 4, env)
        assert "XLA_FLAGS" not in add

    def test_fallback_platform_list_routes_to_accelerator_branch(self):
        # JAX_PLATFORMS="tpu,cpu" initializes the TPU backend (first
        # entry wins), so placement must pin chip visibility — the old
        # substring test would have given every rank the same chips
        add = tp_placement_env(1, 2, {"JAX_PLATFORMS": "tpu,cpu"})
        assert add["TPU_VISIBLE_CHIPS"] == "2,3"
        assert "XLA_FLAGS" not in add
        # and "cpu,tpu" (cpu first) is genuinely the CPU regime
        add = tp_placement_env(1, 2, {"JAX_PLATFORMS": "cpu,tpu"})
        assert "TPU_VISIBLE_CHIPS" not in add
        assert "host_platform_device_count=2" in add["XLA_FLAGS"]

    def test_accelerator_pins_disjoint_chip_groups(self):
        a0 = tp_placement_env(0, 4, {})
        a1 = tp_placement_env(1, 4, {})
        assert a0["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
        assert a1["TPU_VISIBLE_CHIPS"] == "4,5,6,7"
        # visibility IS the placement: each rank meshes from offset 0
        assert a1["SPARKDL_TP_DEVICE_OFFSET"] == "0"

    def test_caller_pinned_visibility_uses_inprocess_offsets(self):
        env = {"TPU_VISIBLE_CHIPS": "0,1,2,3,4,5,6,7"}
        add = tp_placement_env(1, 4, env)
        assert "TPU_VISIBLE_CHIPS" not in add
        assert add["SPARKDL_TP_DEVICE_OFFSET"] == "4"

    def test_explicit_offset_never_overridden(self):
        env = {"TPU_VISIBLE_CHIPS": "0,1", "SPARKDL_TP_DEVICE_OFFSET": "6"}
        assert tp_placement_env(1, 2, env) == {}

    def test_tp_degree_parse(self):
        assert _tp_degree({"SPARKDL_SERVE_TP": "4"}) == 4
        assert _tp_degree({}) == 0
        assert _tp_degree({"SPARKDL_SERVE_TP": ""}) == 0
        # a gang env that ASKS for tp with a value we cannot honor
        # fails the spawn loudly (ranks fighting over chips is worse)
        with pytest.raises(ValueError, match="not an integer"):
            _tp_degree({"SPARKDL_SERVE_TP": "nope"})
        with pytest.raises(ValueError, match="negative"):
            _tp_degree({"SPARKDL_SERVE_TP": "-2"})

    def test_ambient_knob_never_rewrites_an_unrelated_gang(
            self, tmp_path, monkeypatch):
        """A shell-exported SPARKDL_SERVE_TP must NOT inject chip
        visibility into a gang that did not ask for tp placement in
        its OWN env= — only the caller's explicit dict gates it."""
        import json

        from sparkdl_tpu.runner import launcher
        worker = tmp_path / "env_worker.py"
        worker.write_text(
            "import json, os, sys\n"
            "rank = os.environ['SPARKDL_PROCESS_ID']\n"
            "json.dump({k: os.environ.get(k) for k in\n"
            "           ('TPU_VISIBLE_CHIPS', 'SPARKDL_TP_DEVICE_OFFSET')},\n"
            "          open(sys.argv[1] + f'/rank{rank}.json', 'w'))\n")
        monkeypatch.setenv("SPARKDL_SERVE_TP", "2")  # ambient only
        launcher.launch(str(worker), np=1, args=[str(tmp_path)],
                        env={"JAX_PLATFORMS": ""}, timeout_s=60.0,
                        capture=True)
        got = json.load(open(tmp_path / "rank0.json"))
        assert got["TPU_VISIBLE_CHIPS"] is None
        assert got["SPARKDL_TP_DEVICE_OFFSET"] is None
        # the same knob in the CALLER's env= dict does gate placement
        launcher.launch(str(worker), np=1, args=[str(tmp_path)],
                        env={"JAX_PLATFORMS": "", "SPARKDL_SERVE_TP": "2"},
                        timeout_s=60.0, capture=True)
        got = json.load(open(tmp_path / "rank0.json"))
        assert got["TPU_VISIBLE_CHIPS"] == "0,1"


class TestTpMesh:
    def test_offset_env_and_bounds(self, monkeypatch):
        from sparkdl_tpu.serving.backend import tp_mesh
        m = tp_mesh(2)
        assert int(m.shape["tp"]) == 2
        assert [d.id for d in m.devices.flat] == [0, 1]
        monkeypatch.setenv("SPARKDL_TP_DEVICE_OFFSET", "6")
        m2 = tp_mesh(2)
        assert [d.id for d in m2.devices.flat] == [6, 7]
        monkeypatch.setenv("SPARKDL_TP_DEVICE_OFFSET", "7")
        with pytest.raises(ValueError, match="visible"):
            tp_mesh(2)
        monkeypatch.delenv("SPARKDL_TP_DEVICE_OFFSET")
        with pytest.raises(ValueError, match=">= 1"):
            tp_mesh(0)


class TestTp1ExactExistingPath:
    """The ISSUE 14 zero-overhead pin: tp<=1 must construct the EXACT
    single-device backends — same classes (not subclasses), no mesh,
    and byte-for-byte the same compiled program set."""

    def test_tp1_constructs_base_classes(self):
        from sparkdl_tpu.serving.backend import (
            LlamaSlotBackend, PagedLlamaSlotBackend)
        cfg, model, variables = _tiny_model()
        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=32, tp=1)
        assert type(eng.backend) is LlamaSlotBackend
        assert eng.tp_degree == 1
        assert not hasattr(eng.backend, "mesh")
        engp = GenerationEngine.from_model(model, variables, num_slots=2,
                                           max_len=32, block_size=8,
                                           tp=1)
        assert type(engp.backend) is PagedLlamaSlotBackend
        # the per-device byte accounting exists on the base classes too
        # (the whole cache on one device)
        assert eng.kv_pool_device_bytes == \
            _global_kv_bytes(eng.backend.cache)

    def test_explicit_mesh_without_tp_is_inferred_not_dropped(self):
        """A caller who built the Mesh(('tp',)) themselves but forgot
        tp= must get a tensor-parallel engine of the mesh's extent —
        never a silent single-device engine with the full unsharded
        KV footprint."""
        from sparkdl_tpu.serving.backend import (
            TensorParallelLlamaSlotBackend, tp_mesh)
        cfg, model, variables = _tiny_model()
        eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                          max_len=32, mesh=tp_mesh(2))
        assert type(eng.backend) is TensorParallelLlamaSlotBackend
        assert eng.tp_degree == 2

    def test_tp_mesh_disagreement_and_bad_env_raise(self, monkeypatch):
        """tp= disagreeing with the passed mesh's extent would validate
        heads against one degree and shard over another (wrong
        per-device budget math, wrong observables) — reject it; and a
        malformed SPARKDL_SERVE_TP raises instead of silently losing
        tensor parallelism (the SPARKDL_SERVE_SPEC_DRAFT rule)."""
        from sparkdl_tpu.serving.backend import tp_mesh
        cfg, model, variables = _tiny_model()
        with pytest.raises(ValueError, match="disagrees"):
            GenerationEngine.from_model(model, variables, num_slots=2,
                                        max_len=32, tp=4,
                                        mesh=tp_mesh(2))
        # an EXPLICIT tp=1 (the pinned single-device baseline) is a
        # disagreement with a 2-device mesh, not an inference input
        with pytest.raises(ValueError, match="disagrees"):
            GenerationEngine.from_model(model, variables, num_slots=2,
                                        max_len=32, tp=1,
                                        mesh=tp_mesh(2))
        monkeypatch.setenv("SPARKDL_SERVE_TP", "four")
        with pytest.raises(ValueError, match="not an integer"):
            GenerationEngine.from_model(model, variables, num_slots=2,
                                        max_len=32)
        monkeypatch.setenv("SPARKDL_SERVE_TP", "-4")
        with pytest.raises(ValueError, match="negative"):
            GenerationEngine.from_model(model, variables, num_slots=2,
                                        max_len=32)

    def test_scrub_serving_env_removes_and_returns(self, monkeypatch):
        from sparkdl_tpu.serving.engine import scrub_serving_env
        monkeypatch.setenv("SPARKDL_SERVE_KV_POOL_MB", "64")
        monkeypatch.setenv("SPARKDL_TP_DEVICE_OFFSET", "4")
        monkeypatch.setenv("SPARKDL_METRICS_DIR", "/tmp/keep")
        import os
        removed = scrub_serving_env()
        assert removed == {"SPARKDL_SERVE_KV_POOL_MB": "64",
                           "SPARKDL_TP_DEVICE_OFFSET": "4"}
        assert "SPARKDL_SERVE_KV_POOL_MB" not in os.environ
        assert os.environ["SPARKDL_METRICS_DIR"] == "/tmp/keep"
        os.environ.update(removed)  # restorable (monkeypatch undoes)
        # dict form: scrubs a COPY the caller owns, same key policy
        env = {"SPARKDL_SERVE_TP": "2", "OTHER": "x"}
        assert scrub_serving_env(env) == {"SPARKDL_SERVE_TP": "2"}
        assert env == {"OTHER": "x"}

    def test_tp1_signature_equality_with_plain_construction(self):
        """Run the same workload through ``from_model(tp=1)`` and a
        plain-constructed backend: the compile-cache signature sets
        must not grow — tp=1 is the same program, not a wrapper."""
        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.serving.backend import LlamaSlotBackend
        cfg, model, variables = _tiny_model()
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, cfg.vocab_size, 5).tolist()

        eng = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=32, prefill_chunk=8,
            prefix_cache_mb=0, tp=1)
        h = eng.submit(prompt, max_new_tokens=3)
        eng.run_until_idle()
        sig_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        sig_c = GLOBAL_COMPILE_CACHE.signatures("serve_prefill_chunk")

        eng2 = GenerationEngine(
            LlamaSlotBackend(model, variables, 2, 32,
                             prefix_cache_bytes=0),
            prefill_chunk=8)
        h2 = eng2.submit(prompt, max_new_tokens=3)
        eng2.run_until_idle()
        assert h2.result(1) == h.result(1)
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") == sig_d
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_prefill_chunk") == sig_c


class TestTpEngineOnCpu:
    def test_tp2_composition_identity_lean(self):
        """The ISSUE 14 lean fast test: ONE tp=2 engine through paged
        block tables × radix graft × chunked prefill × speculation ×
        mid-decode preemption-resume — greedy output bit-identical to
        static ``generate()``, per-device KV pool bytes exactly 1/2 of
        the pool's global bytes, zero decode/verify re-traces after
        warmup, and the tp gauges landing when the plane is armed.
        (The full degree × layout matrix is the ``slow`` twin below.)"""
        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.runner import telemetry
        from sparkdl_tpu.serving.backend import (
            TensorParallelPagedLlamaSlotBackend)
        from sparkdl_tpu.serving.draft import HistoryDraft

        cfg, model, variables = _tiny_model()
        rng = np.random.RandomState(7)
        max_len, new = 64, 10
        head = rng.randint(0, cfg.vocab_size, 16).tolist()  # 2 blocks
        pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()
        refs = _static_refs(model, variables, [pa, pb], new, max_len)

        prov = HistoryDraft()
        prov.observe(pa, refs[0])  # warm retrieval: verify windows run
        prov.observe(pb, refs[1])  # with high acceptance every step
        base_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        base_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        telemetry.reset()
        telemetry.start()
        try:
            eng = GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=max_len,
                prefill_chunk=8, block_size=8, prefill_budget=16,
                spec_k=3, draft_provider=prov, tp=2)
            assert type(eng.backend) is TensorParallelPagedLlamaSlotBackend
            assert eng.paged and eng.tp_degree == 2
            ha = eng.submit(pa, max_new_tokens=new)
            eng.step()  # 2 of pa's 3 chunks (budget 16)
            eng.step()  # final chunk + first token
            eng.step()  # >= 1 speculative verify
            # NOTE: signatures are keyed on traced shapes, which other
            # tests' engines may share — "a verify ran" is pinned via
            # engine stats, the signature set only via non-growth below.
            sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
            assert eng.stats["spec_verifies"] >= 1
            assert ha.state == "running" and 0 < len(ha.tokens) < new
            eng._preempt_newest([(ha.slot, ha)])
            hb = eng.submit(pb, max_new_tokens=new)  # grafts pa's head
            eng.run_until_idle()
            assert ha.result(1) == refs[0]  # resumed, bit-exact
            assert hb.result(1) == refs[1]  # grafted, bit-exact
            snap = eng.snapshot()
            assert snap["preemptions"] == 1
            assert snap["spec_verifies"] >= 1
            assert (snap.get("prefix_cache") or {}).get("hits", 0) >= 1
            # allocation/graft/preempt/resume never re-trace under tp:
            # this engine adds at most ONE decode and at most ONE
            # verify signature over its whole lifetime (the cache is
            # process-global, so compare deltas — a second new
            # signature would be the re-trace regression), and the
            # preempt-resume half adds NONE at all
            assert GLOBAL_COMPILE_CACHE.signatures(
                "serve_decode_step") - base_d <= 1
            assert GLOBAL_COMPILE_CACHE.signatures(
                "serve_verify_step") - base_v <= 1
            assert GLOBAL_COMPILE_CACHE.signatures(
                "serve_verify_step") == sig_v  # none after preempt
            # per-device pool bytes: exactly half the global pool, and
            # exported through snapshot + the armed-plane gauges
            total = _global_kv_bytes(eng.backend.cache)
            assert eng.kv_pool_device_bytes * 2 == total
            assert snap["tp_degree"] == 2
            assert snap["kv_pool_device_bytes"] == \
                eng.kv_pool_device_bytes
            reg = telemetry.registry()
            assert reg.gauge("serving_tp_degree").snapshot()["max"] == 2
            assert reg.gauge(
                "serving_kv_pool_device_bytes").snapshot()["value"] == \
                eng.kv_pool_device_bytes
            # the live inspector names the degree + per-device bytes
            dbg = eng.debug_state()
            assert dbg["tp_degree"] == 2
            assert dbg["kv_pool_device_bytes"] == \
                eng.kv_pool_device_bytes
        finally:
            telemetry.reset()

    def test_tp2_sharded_decode_kernels_token_identity(self, monkeypatch):
        """ISSUE 15: with ``SPARKDL_SERVE_TP_KERNEL=1`` (forced — auto
        is TPU-only) the tp engines stop riding dense cache attention:
        the paged backend dispatches the paged flash-decode kernel and
        the unpaged backend the dense flash-decode kernel, each under
        ``shard_map`` over the head axis — and the greedy streams stay
        bit-identical to static ``generate()``. Odd slot counts keep
        the jit signatures private to this test (the cache keys on
        traced shapes, not the env knob — a kernel-off program traced
        by the other tp tests must not be reused here)."""
        monkeypatch.setenv("SPARKDL_SERVE_TP_KERNEL", "1")
        cfg, model, variables = _tiny_model()
        rng = np.random.RandomState(19)
        new = 6
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 11, 8)]
        refs = _static_refs(model, variables, prompts, new, 128)

        # paged: block_size 8 passes the paged supports(); auto mode
        # engages because the sharded dense dispatch is forced on
        engp = GenerationEngine.from_model(
            model, variables, num_slots=3, max_len=48, block_size=8,
            prefill_chunk=8, tp=2)
        hs = [engp.submit(p, max_new_tokens=new) for p in prompts]
        engp.run_until_idle()
        assert [h.result(1) for h in hs] == refs
        # unpaged: max_len 128 = the dense kernel's KV-block multiple
        engd = GenerationEngine.from_model(
            model, variables, num_slots=3, max_len=128,
            prefill_chunk=8, tp=2)
        hs = [engd.submit(p, max_new_tokens=new) for p in prompts]
        engd.run_until_idle()
        assert [h.result(1) for h in hs] == refs

    def test_tp_int8_token_parity(self, monkeypatch):
        """ISSUE 18: the int8-quantized paged engine (int8 KV codes +
        scale plane + int8 projection weights) is degree-invariant —
        tp ∈ {1, 2} and kernel-off vs kernel-forced all emit the SAME
        greedy streams. int8 may legitimately differ from the f32
        static reference; it may NOT differ across shardings of the
        same quantized program (the scale plane sharding with heads and
        the absmax channel scales sharding with their projections are
        exactly what this pins). Odd slot count keeps jit signatures
        private (the shape-keyed cache rule from the kernel test)."""
        import jax

        cfg, model, variables = _tiny_model()
        rng = np.random.RandomState(23)
        new = 8
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (4, 9, 13)]

        def run(tp, kernel):
            # one knob per backend family: paged single-device vs the
            # shard_map head-sharded dispatch under tp
            monkeypatch.setenv("SPARKDL_SERVE_PAGED_KERNEL", kernel)
            monkeypatch.setenv("SPARKDL_SERVE_TP_KERNEL", kernel)
            eng = GenerationEngine.from_model(
                model, variables, num_slots=3, max_len=48, block_size=8,
                prefill_chunk=8, kv_dtype="int8", weight_dtype="int8",
                tp=tp)
            hs = [eng.submit(p, max_new_tokens=new) for p in prompts]
            eng.run_until_idle()
            return [h.result(1) for h in hs], eng

        base, _ = run(1, "0")
        assert all(len(s) == new for s in base)
        for tp, kernel in ((1, "1"), (2, "0"), (2, "1")):
            got, eng = run(tp, kernel)
            assert got == base, (tp, kernel)
        # the last engine is tp=2 kernel-forced: codes halve per device
        # and the scale plane shards over its head axis alongside them
        # (kv_pool_device_bytes counts BOTH — codes + the 3-dim plane)
        import jax.tree_util as jtu
        plane_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jtu.tree_leaves(eng.backend.cache)
            if getattr(x, "ndim", 0) == 3)
        assert plane_bytes > 0
        assert eng.kv_pool_device_bytes * 2 == \
            _global_kv_bytes(eng.backend.cache) + plane_bytes
        plane = eng.backend.cache["layer_0"]["attn"]["kv_scale"]
        # jax normalizes away the trailing None of P(None, 'tp', None)
        assert plane.sharding.spec == \
            jax.sharding.PartitionSpec(None, "tp")
        ps = eng.backend.pool_stats()
        assert ps["kv_dtype"] == "int8"
        assert ps["kv_scale_bytes_per_block"] > 0

    def test_tp_gauges_zero_registration_when_plane_off(self):
        from sparkdl_tpu.runner import telemetry
        from sparkdl_tpu.serving import StubBackend
        assert not telemetry.enabled()
        eng = GenerationEngine(StubBackend(2, 32), prefill_chunk=8)
        h = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.run_until_idle()
        assert h.result(1)
        assert eng.tp_degree == 1  # duck-typed default
        assert eng.kv_pool_device_bytes is None  # stub has no pool
        assert telemetry.registry().snapshot()["gauges"] == {}

    @pytest.mark.slow
    def test_tp_full_matrix(self):
        """The full composition matrix: tp ∈ {2, 4} × {paged+spec,
        unpaged no-spec}, every stream identical to the tp=1 engine
        AND to static generate(); per-device bytes at 1/tp."""
        from sparkdl_tpu.serving.draft import HistoryDraft

        cfg, model, variables = _tp4_model()
        rng = np.random.RandomState(5)
        max_len, new = 64, 8
        head = rng.randint(0, cfg.vocab_size, 16).tolist()
        prompts = [head + rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (3, 6, 11)]
        refs = _static_refs(model, variables, prompts, new, max_len)

        for paged in (True, False):
            streams, dev_bytes = {}, {}
            for tp in (1, 2, 4):
                kw = dict(num_slots=2, max_len=max_len, prefill_chunk=8,
                          tp=tp)
                if paged:
                    prov = HistoryDraft()
                    for p, r in zip(prompts, refs):
                        prov.observe(p, r)
                    kw.update(block_size=8, prefill_budget=16, spec_k=3,
                              draft_provider=prov)
                eng = GenerationEngine.from_model(model, variables, **kw)
                hs = [eng.submit(p, max_new_tokens=new) for p in prompts]
                eng.run_until_idle()
                streams[tp] = [h.result(1) for h in hs]
                dev_bytes[tp] = eng.kv_pool_device_bytes
            assert streams[1] == refs, f"paged={paged}: tp=1 != static"
            assert streams[2] == refs and streams[4] == refs, \
                f"paged={paged}: tp engine diverged"
            assert dev_bytes[2] * 2 == dev_bytes[1]
            assert dev_bytes[4] * 4 == dev_bytes[1]

    def test_per_device_kv_pool_mb_budget_buys_tp_times_blocks(self):
        """SPARKDL_SERVE_KV_POOL_MB is a PER-DEVICE budget under tp:
        the same MB figure must buy ~tp× the pool blocks (each device
        holds 1/tp of every block)."""
        cfg, model, variables = _tp4_model()
        mb = 0.25
        eng1 = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=32, block_size=8,
            kv_pool_mb=mb, tp=1)
        eng2 = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=32, block_size=8,
            kv_pool_mb=mb, tp=2)
        b1 = eng1.backend.pool_blocks
        b2 = eng2.backend.pool_blocks
        assert b2 >= 2 * b1 - 1, (b1, b2)  # -1: trash-block rounding
        # and the per-device bytes stay inside the budget either way
        assert eng2.kv_pool_device_bytes <= mb * 2 ** 20
