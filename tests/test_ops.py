"""Pallas kernel tests — flash attention vs the dense reference.

Runs through the Pallas interpreter on the CPU test mesh (conftest), exactly
the semantics the compiled TPU kernel executes.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.ops import flash_attention
from sparkdl_tpu.parallel.ring_attention import dense_attention
from sparkdl_tpu.utils.platform import is_tpu_backend

# Compiled-on-TPU runs (SPARKDL_TEST_PLATFORM=axon) compare against a dense
# reference that XLA computes with the MXU's default f32 precision (bf16
# passes), so elementwise agreement is ~1e-4, not 1e-6 — round-5 on-chip
# measurement: max|Δ| 2.8e-4 on the forward. Interpret mode stays tight.
FWD_ATOL = 2e-3 if is_tpu_backend() else 2e-5
BWD_ATOL = 5e-3 if is_tpu_backend() else 5e-4
MODEL_ATOL = 5e-3 if is_tpu_backend() else 1e-3


def _rand_qkv(b=2, h=3, s=128, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _rand_qkv()
    o = flash_attention(q, k, v, causal, block_q=64, block_k=64)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=FWD_ATOL)


@pytest.mark.parametrize("s", [100, 96, 130, 64])
def test_ragged_sequence_lengths(s):
    q, k, v = _rand_qkv(s=s, seed=s)
    o = flash_attention(q, k, v, True, block_q=64, block_k=32)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=FWD_ATOL)


@pytest.mark.parametrize("s", [4, 37, 100, 130])
def test_ragged_with_default_blocks(s):
    """Arbitrary sequence lengths through the DEFAULT (128) blocks — the
    shapes the generation-UDF prefill hands the kernel on TPU. Blocks stay
    lane-aligned; S pads up inside _fwd."""
    q, k, v = _rand_qkv(s=s, seed=s)
    o = flash_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(dense_attention(q, k, v, True)),
                               atol=FWD_ATOL)
    lens = np.minimum([s, max(1, s // 2)], s)
    kv_mask = jnp.asarray((np.arange(s)[None, :]
                           < np.asarray(lens)[:, None]).astype(np.float32))
    o2 = flash_attention(q, k, v, False, kv_mask=kv_mask)
    np.testing.assert_allclose(
        np.asarray(o2), np.asarray(_masked_dense(q, k, v, kv_mask, False)),
        atol=FWD_ATOL)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _rand_qkv(s=96, d=16)

    def lf(a, b, c):
        return (flash_attention(a, b, c, causal, block_q=32, block_k=32) ** 2).sum()

    def lr(a, b, c):
        return (dense_attention(a, b, c, causal) ** 2).sum()

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=BWD_ATOL)


def test_bf16_inputs():
    q, k, v = [x.astype(jnp.bfloat16) for x in _rand_qkv()]
    o = flash_attention(q, k, v, True)
    assert o.dtype == jnp.bfloat16
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(o, dtype=np.float32),
                               np.asarray(ref), atol=3e-2)


def test_jit_and_blocks_smaller_than_seq():
    q, k, v = _rand_qkv(s=256)
    f = jax.jit(lambda a, b, c: flash_attention(a, b, c, True, block_q=128, block_k=64))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(dense_attention(q, k, v, True)),
                               atol=FWD_ATOL)


def test_llama_with_flash_attention():
    """flash_attention drops into LlamaModel's attn_fn slot.

    The reference arm pins ``attn_fn=None`` (in-model XLA dense) so the
    comparison does not depend on what the platform's "auto" policy
    resolves to.  COMPILED on the chip this is a real two-implementation
    comparison: the kernel's MXU dots and XLA's fused dense attention
    round f32 differently (isolated-kernel parity is ~1.8e-3,
    bench flash leg), and the per-layer delta is amplified through the
    model's layers and the vocab projection onto O(1)-magnitude logits —
    the 2026-07-31 on-chip run measured max 0.041 — so the model-level
    bound is wider than the kernel-level one, with a mean bound keeping
    sensitivity to real masking/offset bugs (which shift whole rows, not
    rounding tails)."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, size=(2, 32))
    base = LlamaModel(cfg, attn_fn=None)
    variables = base.init(jax.random.PRNGKey(0), jnp.asarray(ids))
    logits_dense = base.apply(variables, jnp.asarray(ids))
    flash_model = LlamaModel(cfg, attn_fn=flash_attention)
    logits_flash = flash_model.apply(variables, jnp.asarray(ids))
    diff = np.abs(np.asarray(logits_flash) - np.asarray(logits_dense))
    atol = 6e-2 if is_tpu_backend() else MODEL_ATOL
    assert diff.max() < atol, f"max {diff.max():.4f} >= {atol}"
    assert diff.mean() < atol / 6, f"mean {diff.mean():.4f} >= {atol / 6}"


def _masked_dense(q, k, v, kv_mask, causal):
    import math
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
    valid = kv_mask[:, None, None, :].astype(bool)
    if causal:
        S = q.shape[2]
        valid = valid & jnp.tril(jnp.ones((S, S), bool))[None, None]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_mask_matches_masked_dense(causal):
    """Padded key positions (the BERT attention-mask contract) are excluded
    from every query's softmax — forward and gradients."""
    q, k, v = _rand_qkv(s=96, d=16, seed=7)
    lens = np.array([96, 40])
    kv_mask = jnp.asarray((np.arange(96)[None, :] < lens[:, None])
                          .astype(np.float32))
    o = flash_attention(q, k, v, causal, kv_mask=kv_mask,
                        block_q=32, block_k=32)
    ref = _masked_dense(q, k, v, kv_mask, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=FWD_ATOL)

    gf = jax.grad(lambda a: (flash_attention(
        a, k, v, causal, kv_mask=kv_mask, block_q=32, block_k=32) ** 2)
        .sum())(q)
    gr = jax.grad(lambda a: (_masked_dense(a, k, v, kv_mask, causal) ** 2)
                  .sum())(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=BWD_ATOL)


def test_fully_masked_rows_produce_zeros():
    q, k, v = _rand_qkv(s=32, d=16, seed=9)
    kv_mask = jnp.zeros((2, 32))  # nothing attendable
    o = flash_attention(q, k, v, False, kv_mask=kv_mask,
                        block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(o), 0.0, atol=1e-6)


def test_auto_attn_fn_policy():
    from sparkdl_tpu.ops.flash_attention import adaptive_attention, auto_attn_fn
    fn = auto_attn_fn()
    if is_tpu_backend():
        assert fn is adaptive_attention
    else:
        assert fn is None


def test_adaptive_attention_arms():
    """Both arms of the length-adaptive policy agree with the dense
    reference, with and without kv_mask, on either side of the
    SPARKDL_FLASH_MIN_SEQ crossover (forced low to reach the flash arm
    at test-scale shapes)."""
    from sparkdl_tpu.ops.flash_attention import adaptive_attention

    q, k, v = _rand_qkv(s=64, seed=11)
    ref = dense_attention(q, k, v, True)
    # dense arm (64 < min_seq default)
    np.testing.assert_allclose(np.asarray(adaptive_attention(q, k, v, True)),
                               np.asarray(ref), atol=FWD_ATOL)
    # flash arm, forced by dropping the crossover below s
    os.environ["SPARKDL_FLASH_MIN_SEQ"] = "32"
    try:
        np.testing.assert_allclose(
            np.asarray(adaptive_attention(q, k, v, True)),
            np.asarray(ref), atol=FWD_ATOL)
    finally:
        del os.environ["SPARKDL_FLASH_MIN_SEQ"]
    # kv_mask contract holds on the dense arm (flash arm's is kernel-tested)
    kv_mask = jnp.asarray(np.r_[np.ones(40), np.zeros(24)][None, :]
                          .repeat(2, 0).astype(np.float32))
    got = adaptive_attention(q, k, v, False, kv_mask=kv_mask)
    sc = np.einsum("bhqd,bhkd->bhqk",
                   np.asarray(q), np.asarray(k)) / np.sqrt(q.shape[-1])
    sc = np.where(np.asarray(kv_mask)[:, None, None, :] > 0, sc, -1e30)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))
    np.testing.assert_allclose(np.asarray(got), want, atol=FWD_ATOL)
    # fully-masked rows output ZEROS on the dense arm too — the flash
    # kernel's contract (test_fully_masked_rows_produce_zeros), which a
    # finite NEG_INF softmax would otherwise turn into mean(v)
    all_dead = jnp.zeros((2, 64))
    o0 = adaptive_attention(q, k, v, False, kv_mask=all_dead)
    np.testing.assert_allclose(np.asarray(o0), 0.0, atol=1e-6)


def test_is_tpu_device_recognizes_axon():
    """The axon plugin registers platform "axon" with TPU device_kind;
    the gate must fire on it (round-3 verdict missing #2)."""
    from sparkdl_tpu.utils.platform import is_tpu_device

    class _Fake:
        def __init__(self, platform, device_kind):
            self.platform, self.device_kind = platform, device_kind

    assert is_tpu_device(_Fake("tpu", "TPU v4"))
    assert is_tpu_device(_Fake("axon", "TPU v5 lite"))
    assert is_tpu_device(_Fake("weird", "TPU v5e"))
    assert not is_tpu_device(_Fake("cpu", "cpu"))
    assert not is_tpu_device(_Fake("gpu", "NVIDIA H100"))


@pytest.mark.skipif(
    not is_tpu_backend(),
    reason="compiled-mode kernel needs a real TPU "
           "(run with SPARKDL_TEST_PLATFORM=axon)")
def test_compiled_flash_on_tpu():
    """COMPILED (non-interpret) kernel on the chip: forward + grads vs the
    dense reference, causal and masked variants (round-2 verdict weak #3)."""
    q, k, v = _rand_qkv(s=256, d=64)
    o = flash_attention(q, k, v, True, interpret=False)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(dense_attention(q, k, v, True)),
                               atol=2e-3)
    lens = np.array([256, 100])
    kv_mask = jnp.asarray((np.arange(256)[None, :] < lens[:, None])
                          .astype(np.float32))
    o2 = flash_attention(q, k, v, False, kv_mask=kv_mask, interpret=False)
    np.testing.assert_allclose(
        np.asarray(o2), np.asarray(_masked_dense(q, k, v, kv_mask, False)),
        atol=2e-3)
    g = jax.grad(lambda a: (flash_attention(
        a, k, v, True, interpret=False) ** 2).sum())(q)
    gr = jax.grad(lambda a: (dense_attention(a, k, v, True) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=5e-2)
