"""Pallas kernel tests — flash attention vs the dense reference.

Runs through the Pallas interpreter on the CPU test mesh (conftest), exactly
the semantics the compiled TPU kernel executes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.ops import flash_attention
from sparkdl_tpu.parallel.ring_attention import dense_attention


def _rand_qkv(b=2, h=3, s=128, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, h, s, d).astype(np.float32) * 0.3)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _rand_qkv()
    o = flash_attention(q, k, v, causal, 64, 64)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("s", [100, 96, 130, 64])
def test_ragged_sequence_lengths(s):
    q, k, v = _rand_qkv(s=s, seed=s)
    o = flash_attention(q, k, v, True, 64, 32)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _rand_qkv(s=96, d=16)

    def lf(a, b, c):
        return (flash_attention(a, b, c, causal, 32, 32) ** 2).sum()

    def lr(a, b, c):
        return (dense_attention(a, b, c, causal) ** 2).sum()

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_bf16_inputs():
    q, k, v = [x.astype(jnp.bfloat16) for x in _rand_qkv()]
    o = flash_attention(q, k, v, True)
    assert o.dtype == jnp.bfloat16
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(o, dtype=np.float32),
                               np.asarray(ref), atol=3e-2)


def test_jit_and_blocks_smaller_than_seq():
    q, k, v = _rand_qkv(s=256)
    f = jax.jit(lambda a, b, c: flash_attention(a, b, c, True, 128, 64))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(dense_attention(q, k, v, True)),
                               atol=2e-5)


def test_llama_with_flash_attention():
    """flash_attention drops into LlamaModel's attn_fn slot."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, size=(2, 32))
    base = LlamaModel(cfg)
    variables = base.init(jax.random.PRNGKey(0), jnp.asarray(ids))
    logits_dense = base.apply(variables, jnp.asarray(ids))
    flash_model = LlamaModel(cfg, attn_fn=flash_attention)
    logits_flash = flash_model.apply(variables, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(logits_flash),
                               np.asarray(logits_dense), atol=1e-3)
