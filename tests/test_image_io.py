"""Image I/O tests: struct schema, converters, decode/resize, readers."""

import numpy as np
import pytest

from sparkdl_tpu.image import imageIO


def rand_img(h=8, w=6, c=3, dtype=np.uint8, seed=0):
    rng = np.random.default_rng(seed)
    if dtype == np.uint8:
        return rng.integers(0, 256, size=(h, w, c), dtype=np.uint8)
    return rng.random((h, w, c), dtype=np.float32)


def test_array_struct_roundtrip_uint8_and_float():
    for dtype in (np.uint8, np.float32):
        img = rand_img(dtype=dtype)
        s = imageIO.imageArrayToStruct(img, origin="mem")
        assert s["height"] == 8 and s["width"] == 6 and s["nChannels"] == 3
        back = imageIO.imageStructToArray(s)
        assert back.dtype == img.dtype
        np.testing.assert_array_equal(back, img)


def test_mode_codes_match_opencv_numbering():
    # Spark ImageSchema / OpenCV type codes: CV_8UC3 == 16, CV_8UC1 == 0.
    assert imageIO.imageArrayToStruct(rand_img(c=3))["mode"] == 16
    assert imageIO.imageArrayToStruct(rand_img(c=1))["mode"] == 0
    assert imageIO.imageArrayToStruct(rand_img(c=4))["mode"] == 24
    assert imageIO.imageArrayToStruct(
        rand_img(dtype=np.float32))["mode"] == 21
    with pytest.raises(ValueError):
        imageIO.ocvTypeByMode(99)


def test_grayscale_2d_promoted():
    img2d = np.zeros((4, 5), dtype=np.uint8)
    s = imageIO.imageArrayToStruct(img2d)
    assert s["nChannels"] == 1
    assert imageIO.imageStructToArray(s).shape == (4, 5, 1)


def test_decode_encode_png_roundtrip():
    img = rand_img()
    png = imageIO.encodePng(imageIO.imageArrayToStruct(img))
    s = imageIO.decodeImage(png, origin="x.png")
    assert s is not None and s["origin"] == "x.png"
    np.testing.assert_array_equal(imageIO.imageStructToArray(s), img)


def test_decode_garbage_returns_none():
    assert imageIO.decodeImage(b"not an image") is None


def test_resize():
    img = rand_img(h=10, w=10)
    s = imageIO.resizeImage(imageIO.imageArrayToStruct(img), 4, 6)
    assert (s["height"], s["width"]) == (4, 6)
    arr = imageIO.imageStructToArray(s)
    assert arr.shape == (4, 6, 3)


def test_structs_to_nhwc_mixed_sizes():
    imgs = [rand_img(8, 8, 3, seed=i) for i in range(3)]
    structs = [imageIO.imageArrayToStruct(im) for im in imgs]
    structs.append(imageIO.imageArrayToStruct(rand_img(16, 12, 3, seed=9)))
    batch = imageIO.structsToNHWC(structs, height=8, width=8)
    assert batch.shape == (4, 8, 8, 3)
    assert batch.dtype == np.float32
    # structs store BGR at rest; default output is RGB (flipped)
    np.testing.assert_allclose(batch[0], imgs[0][:, :, ::-1].astype(np.float32))
    raw = imageIO.structsToNHWC(structs, height=8, width=8, channelOrder="BGR")
    np.testing.assert_allclose(raw[0], imgs[0].astype(np.float32))


def test_structs_to_nhwc_channel_mismatch_raises():
    structs = [imageIO.imageArrayToStruct(rand_img(c=3)),
               imageIO.imageArrayToStruct(rand_img(c=1))]
    with pytest.raises(ValueError, match="channel mismatch"):
        imageIO.structsToNHWC(structs)


def test_resize_batch_nhwc_xla():
    batch = np.stack([rand_img(12, 12, 3, seed=i) for i in range(2)]
                     ).astype(np.float32)
    out = imageIO.resizeImageBatchNHWC(batch, 6, 6)
    assert out.shape == (2, 6, 6, 3)


def test_read_images_dir(tmp_path):
    from PIL import Image
    for i in range(4):
        Image.fromarray(rand_img(seed=i)).save(tmp_path / f"img_{i}.png")
    (tmp_path / "junk.png").write_bytes(b"broken")
    (tmp_path / "notes.txt").write_text("ignored")

    df = imageIO.readImages(str(tmp_path), numPartitions=2)
    rows = df.collect()
    assert len(rows) == 4  # broken file dropped, txt ignored
    assert df.numPartitions == 2
    img0 = rows[0].image
    assert img0["mode"] == 16 and img0["height"] == 8
    assert img0["origin"].endswith(".png")

    with pytest.raises(FileNotFoundError):
        imageIO.readImages(str(tmp_path / "empty-dir"))


def test_create_resize_image_udf():
    import sparkdl_tpu as sdl

    structs = [imageIO.imageArrayToStruct(rand_img(seed=i, h=12, w=10))
               for i in range(4)]
    df = sdl.DataFrame.fromPydict({"image": structs})
    out = df.withColumn("small", sdl.createResizeImageUDF(6, 5), ["image"])
    rows = out.collect()
    assert rows[0]["small"]["height"] == 6
    assert rows[0]["small"]["width"] == 5
    assert rows[0]["image"]["height"] == 12  # source untouched


def test_read_images_sample_ratio(tmp_path):
    from PIL import Image
    for i in range(40):
        Image.fromarray(rand_img(seed=i)).save(tmp_path / f"img_{i:02d}.png")
    full = imageIO.readImages(str(tmp_path)).count()
    assert full == 40
    n1 = imageIO.readImages(str(tmp_path), sampleRatio=0.5, seed=7).count()
    n2 = imageIO.readImages(str(tmp_path), sampleRatio=0.5, seed=7).count()
    assert n1 == n2  # seeded → reproducible
    assert 0 < n1 < 40
    with pytest.raises(ValueError, match="sampleRatio"):
        imageIO.readImages(str(tmp_path), sampleRatio=0.0)


def test_read_images_keep_failures(tmp_path):
    from PIL import Image
    Image.fromarray(rand_img()).save(tmp_path / "ok.png")
    (tmp_path / "bad.png").write_bytes(b"broken")
    df = imageIO.readImages(str(tmp_path), dropImageFailures=False)
    rows = sorted(df.collect(), key=lambda r: r.image["origin"])
    assert rows[0].image["height"] == -1  # failure sentinel row kept
    assert rows[1].image["height"] == 8


def test_read_images_is_lazy(tmp_path):
    """readImages must not decode on the driver at construction time:
    decode runs per-chunk at materialization (round-1 verdict item 4)."""
    from PIL import Image
    for i in range(6):
        Image.fromarray(rand_img(seed=i)).save(tmp_path / f"img_{i}.png")

    calls = []

    def counting_decode(data, origin):
        calls.append(origin)
        return imageIO.decodeImage(data, origin)

    df = imageIO.readImagesWithCustomFn(str(tmp_path),
                                        decode_fn=counting_decode)
    assert calls == []  # nothing decoded yet
    rows = df.collect()
    assert len(rows) == 6
    assert len(calls) == 6


def test_read_images_streams_in_chunks(tmp_path):
    """iterBatches over a lazy readImages frame decodes at batch granularity
    — a single partition of N images never holds all N decoded at once."""
    from PIL import Image
    for i in range(10):
        Image.fromarray(rand_img(seed=i)).save(tmp_path / f"img_{i}.png")

    chunk_sizes = []

    def counting_decode(data, origin):
        counting_decode.pending += 1
        return imageIO.decodeImage(data, origin)

    counting_decode.pending = 0

    df = imageIO.readImagesWithCustomFn(str(tmp_path),
                                        decode_fn=counting_decode,
                                        numPartitions=1)
    for b in df.iterBatches(4):
        chunk_sizes.append(counting_decode.pending)
        counting_decode.pending = 0
    # decode happened in ≤4-row chunks interleaved with batch delivery,
    # not 10-at-once up front
    assert max(chunk_sizes) <= 8  # one chunk + at most one prefetched chunk
    assert sum(chunk_sizes) == 10


def test_read_images_all_failed_raises(tmp_path):
    """A directory of only-corrupt images must fail loudly at materialization
    (the eager reader's guard, preserved by the lazy one)."""
    for i in range(3):
        (tmp_path / f"bad_{i}.png").write_bytes(b"broken")
    df = imageIO.readImages(str(tmp_path))  # lazy: no error yet
    with pytest.raises(ValueError, match="failed to decode"):
        df.collect()


def test_read_images_unreadable_file_raises_when_keeping_failures(tmp_path):
    """dropImageFailures=False surfaces I/O errors (no silent placeholder)."""
    from PIL import Image
    Image.fromarray(rand_img()).save(tmp_path / "ok.png")
    (tmp_path / "gone.png").symlink_to(tmp_path / "nonexistent.png")
    df = imageIO.readImages(str(tmp_path), dropImageFailures=False)
    with pytest.raises(OSError):
        df.collect()
    # and with dropping enabled the bad file is just skipped
    rows = imageIO.readImages(str(tmp_path), dropImageFailures=True).collect()
    assert len(rows) == 1


def test_bgr_at_rest_convention():
    # decodeImage must store BGR (Spark/OpenCV at-rest layout): a pure-red
    # PNG decodes to a struct whose first byte-plane is blue==0, last is red.
    from PIL import Image
    import io as _io
    red = np.zeros((4, 4, 3), np.uint8)
    red[:, :, 0] = 255  # RGB red
    buf = _io.BytesIO()
    Image.fromarray(red).save(buf, format="PNG")
    s = imageIO.decodeImage(buf.getvalue())
    stored = imageIO.imageStructToArray(s)
    assert stored[0, 0, 0] == 0 and stored[0, 0, 2] == 255  # B,G,R order
    # and the model-facing NHWC batch is back in RGB
    batch = imageIO.structsToNHWC([s])
    assert batch[0, 0, 0, 0] == 255 and batch[0, 0, 0, 2] == 0


def test_image_column_to_nhwc_matches_structs_path(tmp_path):
    from PIL import Image
    for i in range(3):
        Image.fromarray(rand_img(seed=i)).save(tmp_path / f"i{i}.png")
    Image.fromarray(rand_img(12, 10, 3, seed=7)).save(tmp_path / "big.png")
    df = imageIO.readImages(str(tmp_path))
    part = next(df.iterPartitions())
    col = part.column("image")
    fast = imageIO.imageColumnToNHWC(col, 8, 6)
    slow = imageIO.structsToNHWC(col.to_pylist(), 8, 6)
    np.testing.assert_array_equal(fast, slow)
    assert fast.shape == (4, 8, 6, 3)


def test_zero_copy_arrow_pack_path(monkeypatch):
    """The Arrow-pointer fast path (addresses straight from the binary
    values buffer + offsets): equals the pure-python path, honors column
    slices (nonzero Arrow offset), and still raises on a row whose byte
    length contradicts its declared shape."""
    import pyarrow as pa

    from sparkdl_tpu import native

    if not native.available():
        pytest.skip("native packer unavailable")
    structs = [imageIO.imageArrayToStruct(rand_img(9, 7, 3, seed=i),
                                          origin=f"s{i}")
               for i in range(6)]
    col = pa.array(structs, type=imageIO.imageSchema)
    fast = imageIO.imageColumnToNHWC(col, 9, 7, dtype=np.uint8)
    monkeypatch.setenv("SPARKDL_TPU_NATIVE", "0")
    ref = imageIO.imageColumnToNHWC(col, 9, 7, dtype=np.uint8)
    monkeypatch.delenv("SPARKDL_TPU_NATIVE")
    np.testing.assert_array_equal(fast, ref)

    sliced = imageIO.imageColumnToNHWC(col.slice(2, 3), 9, 7,
                                       dtype=np.uint8)
    np.testing.assert_array_equal(sliced, ref[2:5])

    bad = [dict(s) for s in structs]
    bad[1]["data"] = bad[1]["data"][:-1]  # truncated payload
    bad_col = pa.array(bad, type=imageIO.imageSchema)
    with pytest.raises(ValueError, match="buffer has"):
        imageIO.imageColumnToNHWC(bad_col, 9, 7, dtype=np.uint8)


def test_nhwc_to_image_column_vectorized():
    """nhwcToImageColumn (vectorized write side) produces a column
    identical to the per-row nhwcToStructs path, and round-trips through
    imageColumnToNHWC."""
    import pyarrow as pa

    batch = np.stack([rand_img(6, 5, 3, seed=i) for i in range(4)])
    origins = [f"o{i}" for i in range(4)]
    fast = imageIO.nhwcToImageColumn(batch, origins=origins)
    slow = pa.array(imageIO.nhwcToStructs(batch, origins=origins),
                    type=imageIO.imageSchema)
    assert fast.equals(slow)
    back = imageIO.imageColumnToNHWC(fast, 6, 5, dtype=np.uint8)
    np.testing.assert_array_equal(back, batch)
    with pytest.raises(ValueError, match="origins"):
        imageIO.nhwcToImageColumn(batch, origins=["x"])
    with pytest.raises(ValueError, match="NHWC"):
        imageIO.nhwcToImageColumn(batch[0])


def test_nhwc_to_image_column_does_not_alias_caller_buffer():
    """Default copy=True: mutating the input batch after conversion must
    not change the column (the no-swap path would otherwise zero-copy
    alias the caller's buffer)."""
    batch = np.stack([rand_img(4, 4, 3, seed=i) for i in range(2)])
    col = imageIO.nhwcToImageColumn(batch, channelOrder="BGR")
    before = imageIO.imageColumnToNHWC(col, 4, 4, dtype=np.uint8).copy()
    batch[:] = 0
    after = imageIO.imageColumnToNHWC(col, 4, 4, dtype=np.uint8)
    np.testing.assert_array_equal(after, before)
