"""Failure detection & classification (SURVEY.md §5.3).

The reference whole-job-retried everything (Spark task retry); here
infrastructure flakes restart while program bugs re-raise immediately —
VERDICT round-1 item 7.
"""

import pytest

from sparkdl_tpu.runner import (TrainingDivergedError, XlaRunner,
                                classify_exception, classify_text,
                                diagnose_context, is_retryable)


class TestClassify:
    @pytest.mark.parametrize("exc", [
        ValueError("bad shape"),
        TypeError("not a pytree"),
        KeyError("missing"),
        AssertionError("nope"),
        RuntimeError("INVALID_ARGUMENT: mismatched dims"),
        RuntimeError("RESOURCE_EXHAUSTED: out of HBM"),
    ])
    def test_fatal(self, exc):
        assert classify_exception(exc) == "fatal"
        assert not is_retryable(exc)

    @pytest.mark.parametrize("exc", [
        RuntimeError("UNAVAILABLE: TPU backend setup/compile error"),
        RuntimeError("DEADLINE_EXCEEDED: collective timed out"),
        RuntimeError("ABORTED: coordination service lost worker 3"),
        ConnectionError("failed to connect to coordinator"),
        TimeoutError("rendezvous"),
        OSError("socket closed"),
        RuntimeError("slice 0 unhealthy: preempted"),
        RuntimeError("some unrecognized runtime condition"),
    ])
    def test_retryable(self, exc):
        assert classify_exception(exc) == "retryable"
        assert is_retryable(exc)

    def test_keyboard_interrupt_fatal(self):
        assert classify_exception(KeyboardInterrupt()) == "fatal"

    def test_training_diverged_fatal(self):
        e = TrainingDivergedError(17, float("nan"))
        assert classify_exception(e) == "fatal"
        assert e.step == 17
        assert "step 17" in str(e)


# Realistic jaxlib/gRPC message strings pinning the retryable/fatal POLICY:
# a regex edit that silently flips any of these rows is a restart-budget
# bug, not a refactor (ISSUE 1 satellite). Messages are verbatim-shaped
# from jaxlib XlaRuntimeError / TF coordination-service / gRPC transport
# errors.
_REALISTIC = [
    ("UNAVAILABLE: failed to connect to all addresses; last error: "
     "UNKNOWN: ipv4:10.130.0.31:8476: Failed to connect to remote host: "
     "Connection refused", "retryable"),
    ("UNAVAILABLE: Socket closed", "retryable"),
    ("DEADLINE_EXCEEDED: Barrier timed out. Barrier_id: "
     "PjRT_Client_Connect. Timed out task names: "
     "/job:jax_worker/replica:0/task:3", "retryable"),
    ("ABORTED: The task /job:jax_worker/replica:0/task:1 is not "
     "registered with the coordination service", "retryable"),
    ("Coordination service agent is in ERROR: Heartbeat timeout from "
     "task /job:jax_worker/replica:0/task:1", "retryable"),
    ("UNAVAILABLE: SliceHealthCheck: slice 0 unhealthy: worker was "
     "preempted by a higher-priority job", "retryable"),
    ("INTERNAL: TPU backend setup failed: device or resource busy",
     "retryable"),
    ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
     "17179869184 bytes", "fatal"),
    ("INVALID_ARGUMENT: Executable expected parameter 0 of shape "
     "f32[8,128] but got f32[8,64]", "fatal"),
    ("FAILED_PRECONDITION: BatchNorm running stats not initialized",
     "fatal"),
    ("UNIMPLEMENTED: dynamic-slice op lowering not supported on this "
     "backend", "fatal"),
]


class TestRealisticMessages:
    """Table-driven policy pins over both classification entry points."""

    @pytest.mark.parametrize("msg,expected", _REALISTIC,
                             ids=[m[:32] for m, _ in _REALISTIC])
    def test_classify_exception_policy(self, msg, expected):
        # XlaRuntimeError is not importable without jaxlib internals;
        # classification goes by message text for RuntimeError-shaped
        # errors, which is exactly how the real one is handled.
        assert classify_exception(RuntimeError(msg)) == expected

    @pytest.mark.parametrize("msg,expected", _REALISTIC,
                             ids=[m[:32] for m, _ in _REALISTIC])
    def test_classify_text_policy(self, msg, expected):
        assert classify_text(
            f"Traceback (most recent call last):\n ...\n"
            f"jaxlib.xla_extension.XlaRuntimeError: {msg}") == expected

    def test_plain_python_errors_fatal_in_both(self):
        assert classify_exception(ValueError("bad operand")) == "fatal"
        assert classify_text("Traceback (most recent call last):\n"
                             "  File \"train.py\", line 3, in <module>\n"
                             "ValueError: bad operand") == "fatal"

    def test_text_fatal_wins_over_teardown_noise(self):
        """A run that died on a program error spews incidental CANCELLED/
        coordination lines during teardown — fatal evidence (status codes
        AND Python traceback names) must win over the noise, or supervise
        relaunches a deterministic user bug until the budget is gone."""
        noisy = ("E0801 coordination_service_agent.cc CANCELLED: "
                 "Cancelled by orchestrator\n"
                 "jaxlib.xla_extension.XlaRuntimeError: INVALID_ARGUMENT: "
                 "shape mismatch")
        assert classify_text(noisy) == "fatal"
        py_noisy = ("E0801 coordination_service_agent.cc CANCELLED: "
                    "Cancelled by orchestrator\n"
                    "Traceback (most recent call last):\n"
                    "  File \"train.py\", line 3, in <module>\n"
                    "ValueError: operands could not be broadcast")
        assert classify_text(py_noisy) == "fatal"

    def test_text_unknown_defaults_retryable(self):
        assert classify_text("worker killed by signal 9") == "retryable"
        assert classify_text("") == "retryable"


class TestRunWithRestarts:
    def test_backend_flake_retries(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            if len(attempts) == 1:
                raise RuntimeError("UNAVAILABLE: backend flaked")
            return "ok"

        out = XlaRunner(np=8).run_with_restarts(main, max_restarts=2,
                                                backoff_s=0.0)
        assert out == "ok"
        assert len(attempts) == 2

    def test_user_bug_does_not_retry(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            raise ValueError("user bug")

        with pytest.raises(ValueError):
            XlaRunner(np=8).run_with_restarts(main, max_restarts=5,
                                              backoff_s=0.0)
        assert len(attempts) == 1

    def test_retry_all_overrides(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            if len(attempts) == 1:
                raise ValueError("flaky assert the user wants retried")
            return "ok"

        out = XlaRunner(np=8).run_with_restarts(
            main, max_restarts=2, backoff_s=0.0, retry_all=True)
        assert out == "ok"
        assert len(attempts) == 2

    def test_budget_exhaustion_reraises(self):
        def main(ctx):
            raise RuntimeError("UNAVAILABLE: forever down")

        with pytest.raises(RuntimeError):
            XlaRunner(np=8).run_with_restarts(main, max_restarts=1,
                                              backoff_s=0.0)


def test_diagnose_context_runs():
    # short interval: the package's collection thread sleeps a full
    # interval before noticing the exit flag (see failures.py docstring)
    with diagnose_context(interval_s=1):
        x = 1 + 1
    assert x == 2
