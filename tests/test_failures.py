"""Failure detection & classification (SURVEY.md §5.3).

The reference whole-job-retried everything (Spark task retry); here
infrastructure flakes restart while program bugs re-raise immediately —
VERDICT round-1 item 7.
"""

import pytest

from sparkdl_tpu.runner import (XlaRunner, classify_exception,
                                diagnose_context, is_retryable)


class TestClassify:
    @pytest.mark.parametrize("exc", [
        ValueError("bad shape"),
        TypeError("not a pytree"),
        KeyError("missing"),
        AssertionError("nope"),
        RuntimeError("INVALID_ARGUMENT: mismatched dims"),
        RuntimeError("RESOURCE_EXHAUSTED: out of HBM"),
    ])
    def test_fatal(self, exc):
        assert classify_exception(exc) == "fatal"
        assert not is_retryable(exc)

    @pytest.mark.parametrize("exc", [
        RuntimeError("UNAVAILABLE: TPU backend setup/compile error"),
        RuntimeError("DEADLINE_EXCEEDED: collective timed out"),
        RuntimeError("ABORTED: coordination service lost worker 3"),
        ConnectionError("failed to connect to coordinator"),
        TimeoutError("rendezvous"),
        OSError("socket closed"),
        RuntimeError("slice 0 unhealthy: preempted"),
        RuntimeError("some unrecognized runtime condition"),
    ])
    def test_retryable(self, exc):
        assert classify_exception(exc) == "retryable"
        assert is_retryable(exc)

    def test_keyboard_interrupt_fatal(self):
        assert classify_exception(KeyboardInterrupt()) == "fatal"


class TestRunWithRestarts:
    def test_backend_flake_retries(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            if len(attempts) == 1:
                raise RuntimeError("UNAVAILABLE: backend flaked")
            return "ok"

        out = XlaRunner(np=8).run_with_restarts(main, max_restarts=2,
                                                backoff_s=0.0)
        assert out == "ok"
        assert len(attempts) == 2

    def test_user_bug_does_not_retry(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            raise ValueError("user bug")

        with pytest.raises(ValueError):
            XlaRunner(np=8).run_with_restarts(main, max_restarts=5,
                                              backoff_s=0.0)
        assert len(attempts) == 1

    def test_retry_all_overrides(self):
        attempts = []

        def main(ctx):
            attempts.append(1)
            if len(attempts) == 1:
                raise ValueError("flaky assert the user wants retried")
            return "ok"

        out = XlaRunner(np=8).run_with_restarts(
            main, max_restarts=2, backoff_s=0.0, retry_all=True)
        assert out == "ok"
        assert len(attempts) == 2

    def test_budget_exhaustion_reraises(self):
        def main(ctx):
            raise RuntimeError("UNAVAILABLE: forever down")

        with pytest.raises(RuntimeError):
            XlaRunner(np=8).run_with_restarts(main, max_restarts=1,
                                              backoff_s=0.0)


def test_diagnose_context_runs():
    # short interval: the package's collection thread sleeps a full
    # interval before noticing the exit flag (see failures.py docstring)
    with diagnose_context(interval_s=1):
        x = 1 + 1
    assert x == 2
