"""The examples/ scripts must actually run — they are the user-facing
entry documentation (the reference shipped runnable examples; stale ones
are worse than none). Each runs in a subprocess on the CPU test platform
with tiny sizes."""

import os
import subprocess
import sys

import pytest

_EX = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _run(name: str, extra_env: dict | None = None, timeout: int = 420):
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable, os.path.join(_EX, name)],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)
    assert proc.returncode == 0, \
        f"{name} failed:\n{proc.stderr[-1500:]}\n{proc.stdout[-500:]}"
    return proc.stdout


@pytest.mark.slow
def test_transfer_learning_example():
    out = _run("transfer_learning.py", {"N_IMAGES": "8"})
    assert "train accuracy" in out


@pytest.mark.slow
def test_distributed_training_example():
    out = _run("distributed_training.py",
               {"STEPS": "3", "BATCH_PER_CHIP": "2"})
    assert "-device DP: loss" in out


def test_long_context_serving_example():
    out = _run("long_context_serving.py")
    assert "bit-identical" in out


def test_generation_serving_example():
    out = _run("generation_serving.py")
    assert "ONE prefill + ONE decode program" in out
    assert "in-repo tokenizer only" in out  # config-5 string path
