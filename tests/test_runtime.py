"""Runtime tests: mesh construction, padding, prefetch pipeline, BatchRunner."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.core import runtime


def test_make_mesh_default_and_2d():
    m = runtime.make_mesh()
    assert m.axis_names == ("data",)
    assert m.devices.size == 8

    m2 = runtime.make_mesh({"data": 4, "model": 2})
    assert m2.axis_names == ("data", "model")
    assert m2.devices.shape == (4, 2)

    m3 = runtime.make_mesh({"data": -1, "model": 2})
    assert m3.devices.shape == (4, 2)

    with pytest.raises(ValueError):
        runtime.make_mesh({"data": 3})
    with pytest.raises(ValueError):
        runtime.make_mesh({"data": -1, "model": -1})


def test_pad_batch():
    x = np.ones((3, 4), np.float32)
    padded, n = runtime.pad_batch(x, 8)
    assert padded.shape == (8, 4) and n == 3
    np.testing.assert_array_equal(padded[3:], np.ones((5, 4)))

    d, n = runtime.pad_batch({"a": x, "b": np.zeros((3,))}, 4)
    assert d["a"].shape == (4, 4) and d["b"].shape == (4,) and n == 3

    same, n = runtime.pad_batch(x, 3)
    assert n == 3 and same.shape == (3, 4)

    with pytest.raises(ValueError):
        runtime.pad_batch(x, 2)


def test_prefetch_to_device_preserves_order_and_content():
    batches = [np.full((2, 2), i, np.float32) for i in range(7)]
    out = list(runtime.prefetch_to_device(iter(batches), size=3))
    assert len(out) == 7
    for i, b in enumerate(out):
        assert isinstance(b, jax.Array)
        np.testing.assert_array_equal(np.asarray(b), batches[i])


def test_prefetch_to_device_threaded_transfer_matches_inline():
    """transfer_workers > 0 (the axon tunnel's concurrent-put mode) must
    preserve order and content exactly like the inline path, including
    sharded placement and an iterator shorter than the in-flight depth."""
    for n in (1, 7):
        batches = [np.full((2, 2), i, np.float32) for i in range(n)]
        out = list(runtime.prefetch_to_device(iter(batches), size=2,
                                              transfer_workers=3))
        assert len(out) == n
        for i, b in enumerate(out):
            assert isinstance(b, jax.Array)
            np.testing.assert_array_equal(np.asarray(b), batches[i])
    mesh = runtime.make_mesh()
    sharding = runtime.data_sharding(mesh)
    (dev_b,) = runtime.prefetch_to_device(
        [np.arange(16, dtype=np.float32).reshape(8, 2)],
        sharding=sharding, transfer_workers=2)
    assert len(dev_b.sharding.device_set) == 8


def test_prefetch_size_zero_yields_everything():
    """size=0 (prefetching disabled) must still stream every batch —
    not silently drop the input."""
    batches = [np.full((2,), i, np.float32) for i in range(3)]
    out = list(runtime.prefetch_to_device(iter(batches), size=0))
    assert len(out) == 3
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b), batches[i])


def test_prefetch_transfer_workers_env_default(monkeypatch):
    monkeypatch.setenv("SPARKDL_TRANSFER_WORKERS", "2")
    assert runtime.transfer_workers_default() == 2
    batches = [np.full((2,), i, np.float32) for i in range(4)]
    out = list(runtime.prefetch_to_device(iter(batches), size=2))
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b), batches[i])


def test_prefetch_sharded_across_mesh():
    mesh = runtime.make_mesh()
    sharding = runtime.data_sharding(mesh)
    batches = [np.arange(16, dtype=np.float32).reshape(8, 2)]
    (dev_b,) = list(runtime.prefetch_to_device(iter(batches), sharding=sharding))
    assert len(dev_b.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(dev_b), batches[0])


def test_batch_runner_pads_runs_unpads():
    traces = []

    def fn(x):
        traces.append(x.shape)
        return x * 2.0

    runner = runtime.BatchRunner(fn, batch_size=4)
    batches = [np.ones((4, 3), np.float32), np.ones((4, 3), np.float32),
               np.ones((2, 3), np.float32)]  # ragged tail
    outs = list(runner.run(iter(batches)))
    assert [o.shape for o in outs] == [(4, 3), (4, 3), (2, 3)]
    np.testing.assert_allclose(outs[2], 2.0)
    # one trace only: static shape held across full and padded batches
    assert traces == [(4, 3)]


def test_batch_runner_dict_batches():
    def fn(d):
        return {"s": d["a"] + d["b"]}

    runner = runtime.BatchRunner(fn, batch_size=4)
    out = next(iter(runner.run([{"a": np.ones((3, 2), np.float32),
                                 "b": np.ones((3, 2), np.float32)}])))
    assert out["s"].shape == (3, 2)
    np.testing.assert_allclose(out["s"], 2.0)


def test_compile_cache_counts():
    cache = runtime.CompileCache()
    f = cache.get("f", lambda x: x + 1)
    f(jnp.ones((2,)))
    f(jnp.ones((2,)))
    f(jnp.ones((3,)))
    assert cache.misses == 2 and cache.hits == 1


def test_batch_runner_input_cast_and_pipelining():
    """uint8 host feed + in-graph cast must match a float32 feed, across a
    stream long enough to exercise the in-flight window (round-3 perf fix:
    fetch of batch k overlaps compute of batch k+1)."""
    fn = lambda b: b.sum(axis=(1, 2, 3))
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, 256, size=(4, 5, 5, 3)).astype(np.uint8)
               for _ in range(7)]
    cast_runner = runtime.BatchRunner(fn, batch_size=4, input_cast=jnp.float32)
    plain_runner = runtime.BatchRunner(fn, batch_size=4)
    got = list(cast_runner.run(iter(batches)))
    want = list(plain_runner.run(b.astype(np.float32) for b in batches))
    assert len(got) == 7
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w)


def test_background_iter_order_and_error():
    assert list(runtime.background_iter(iter(range(20)), maxsize=3)) \
        == list(range(20))

    def boom():
        yield 1
        raise RuntimeError("decode failed")

    it = runtime.background_iter(boom(), maxsize=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_background_iter_cancellation_releases_producer():
    """Abandoning the generator (consumer error path) must unblock the
    producer thread rather than leaving it parked on a full queue forever
    (code-review r3)."""
    import threading
    import time

    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = runtime.background_iter(gen(), maxsize=1)
    assert next(it) == 0
    it.close()  # abandon mid-stream
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "producer thread leaked"
    assert len(produced) < 100, "producer ran unbounded after close"


def test_parallel_map_iter_order_error_and_inline():
    """The decode pool preserves order under parallelism, re-raises at the
    consumption point, and workers<=0 degrades to inline map."""
    import time as _time

    def slow_sq(i):
        _time.sleep(0.01 * ((i * 7) % 3))  # jittered: tempt reordering
        return i * i

    got = list(runtime.parallel_map_iter(slow_sq, range(20), workers=4))
    assert got == [i * i for i in range(20)]
    assert list(runtime.parallel_map_iter(slow_sq, range(5), workers=0)) \
        == [i * i for i in range(5)]

    def boom(i):
        if i == 3:
            raise RuntimeError("decode failed")
        return i

    it = runtime.parallel_map_iter(boom, range(6), workers=2)
    assert next(it) == 0
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_parallel_map_iter_env_default(monkeypatch):
    monkeypatch.setenv("SPARKDL_DECODE_WORKERS", "3")
    assert runtime.decode_workers_default() == 3
    monkeypatch.setenv("SPARKDL_DECODE_WORKERS", "junk")
    assert runtime.decode_workers_default() == 2


def test_run_stream_threads_meta_and_matches_run():
    """run_stream carries host-side metadata through the window untouched
    and unpads exactly like run()."""
    fn = lambda x: x + 1.0
    runner = runtime.BatchRunner(fn, batch_size=4)
    batches = [np.full((3, 2), i, np.float32) for i in range(6)]
    metas = [("part", i) for i in range(6)]
    out = list(runner.run_stream(zip(batches, metas)))
    assert [m for _, m in out] == metas
    for i, (o, _) in enumerate(out):
        assert o.shape == (3, 2)
        np.testing.assert_allclose(o, i + 1.0)
    # meta-less wrapper agrees
    out2 = list(runner.run(iter(batches)))
    for (o, _), o2 in zip(out, out2):
        np.testing.assert_array_equal(o, o2)


def test_run_stream_no_drain_at_partition_boundaries():
    """THE no-drain pin (ISSUE 3 acceptance): with a full prefetch window,
    dispatches run ahead across 'partition' boundaries — before the FIRST
    output is even fetched, chunks of later partitions have already been
    dispatched. The old per-partition run() dispatched exactly one chunk
    per partition before yielding its output."""
    runner = runtime.BatchRunner(lambda x: x * 2.0, batch_size=2,
                                 prefetch=2)
    dispatched = []
    inner = runner._jitted
    runner._jitted = lambda b: (dispatched.append(1), inner(b))[1]
    # 5 single-chunk "partitions"
    stream = runner.run_stream(
        (np.full((2, 2), i, np.float32), i) for i in range(5))
    out0, meta0 = next(stream)
    assert meta0 == 0
    np.testing.assert_allclose(out0, 0.0)
    # window depth prefetch=2 → chunks from partitions 0,1,2 (and with the
    # put lookahead possibly 3) dispatched before partition 0's output was
    # yielded: the window crossed ≥2 partition boundaries without draining.
    assert len(dispatched) >= 3, dispatched
    rest = list(stream)
    assert [m for _, m in rest] == [1, 2, 3, 4]
    assert len(dispatched) == 5


def test_compile_cache_emits_recompile_events():
    from sparkdl_tpu.runner import events
    rec = events.reset()
    try:
        cache = runtime.CompileCache()
        f = cache.get("probe_fn", lambda x: x * 2)
        f(jnp.ones((2,)))
        f(jnp.ones((2,)))
        f(jnp.ones((3,)))
        names = [e["name"] for e in rec.tail()]
        assert names.count("recompile") == 2
        ev = [e for e in rec.tail() if e["name"] == "recompile"][-1]
        assert ev["fn"] == "probe_fn" and ev["misses"] == 2
        assert cache.snapshot() == {"hits": 1, "misses": 2}
    finally:
        events.reset()


def test_enable_persistent_compile_cache(tmp_path, monkeypatch):
    """SPARKDL_COMPILE_CACHE wiring: the jax config points at the dir,
    min-compile-time drops to 0 (small programs cache too), and a compile
    through the enabled cache lands in the stats + the event stream as a
    compile_cache miss (the first process pays; a later process hits —
    pinned end-to-end by scripts/score_smoke.py, slow)."""
    from sparkdl_tpu.runner import events
    cache_dir = str(tmp_path / "xla_cache")
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    rec = events.reset()
    try:
        monkeypatch.setenv(runtime.COMPILE_CACHE_ENV, cache_dir)
        assert runtime.enable_persistent_compile_cache() == cache_dir
        assert jax.config.jax_compilation_cache_dir == cache_dir
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0
        before = runtime.persistent_cache_stats()
        assert before["dir"] == cache_dir
        # unique shape → fresh compile → persistent-cache miss recorded
        jax.jit(lambda x: (x * 3 + 1).sum())(jnp.arange(37.0))
        stats = runtime.persistent_cache_stats()
        assert stats["misses"] > before["misses"]
        assert any(e["name"] == "compile_cache"
                   and e.get("outcome") == "miss" for e in rec.tail())
        # a bad path degrades to no-cache instead of raising (a config
        # typo must never kill every importing process)
        bad = str(tmp_path / "not_a_dir")
        open(bad, "w").close()
        assert runtime.enable_persistent_compile_cache(
            bad + "/cache") is None
    finally:
        events.reset()
        # disarm: the listener goes quiet and stale telemetry clears
        runtime.disable_persistent_compile_cache()
        assert runtime.persistent_cache_stats()["dir"] is None
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)


def test_make_mesh_topology_aware_dispatch(monkeypatch):
    """On multi-chip TPU device sets make_mesh must route through
    mesh_utils.create_device_mesh (ICI-torus-aware placement — BASELINE
    "chip-topology aware"); CPU/virtual devices use the plain reshape, and
    a mesh_utils failure degrades to reshape with a warning, not an error."""
    calls = []

    class FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i

        def __repr__(self):
            return f"FakeTpu({self.id})"

    fakes = [FakeTpu(i) for i in range(8)]

    from jax.experimental import mesh_utils as mu

    def fake_create(shape, devices=None):
        calls.append(tuple(shape))
        return np.array(devices).reshape(shape)

    monkeypatch.setattr(mu, "create_device_mesh", fake_create)
    grid = runtime._device_grid(fakes, [4, 2])
    assert calls == [(4, 2)] and grid.shape == (4, 2)

    # CPU devices: no mesh_utils call
    mesh = runtime.make_mesh({"data": 4, "model": 2},
                             devices_=jax.devices()[:8])
    assert mesh.shape == {"data": 4, "model": 2}
    assert calls == [(4, 2)]  # unchanged — cpu path didn't call it

    # mesh_utils blowing up degrades to reshape
    def boom(shape, devices=None):
        raise ValueError("no topology")

    monkeypatch.setattr(mu, "create_device_mesh", boom)
    grid = runtime._device_grid(fakes, [8])
    assert [d.id for d in grid] == list(range(8))


# ---------------------------------------------------------------------------
# run_stream fault tolerance (ISSUE 4): bounded retry, give-up, stall
# ---------------------------------------------------------------------------

@pytest.fixture
def fast_backoff(monkeypatch):
    monkeypatch.setenv("SPARKDL_DISPATCH_BACKOFF_S", "0.01")
    from sparkdl_tpu.runner import chaos, events, metrics
    metrics.run_stats.reset()
    rec = events.reset()
    yield rec
    chaos.uninstall()
    events.reset()
    metrics.run_stats.reset()


@pytest.mark.chaos
def test_dispatch_transient_fault_retried_once(fast_backoff):
    """ISSUE 4 acceptance: an injected once-only dispatch preemption is
    retried and the job succeeds, with a `retry` event recorded."""
    from sparkdl_tpu.runner import metrics
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan, install
    install(FaultPlan([Fault("dispatch", "preempt", prob=1.0, once=True)]))
    r = runtime.BatchRunner(lambda b: b * 2.0, 4)
    out = list(r.run(iter([np.ones((4, 2), np.float32),
                           np.full((3, 2), 3.0, np.float32)])))
    assert len(out) == 2
    np.testing.assert_allclose(out[0], 2.0)
    np.testing.assert_allclose(out[1], 6.0)
    assert out[1].shape == (3, 2)  # pad rows still sliced on the retry path
    names = [e["name"] for e in fast_backoff.tail()]
    assert "retry" in names and "give_up" not in names
    assert metrics.run_stats.dispatch_retries == 1


@pytest.mark.chaos
def test_dispatch_persistent_fault_exhausts_backoff(fast_backoff):
    """A persistent retryable fault exhausts the budget and fails with a
    classified error naming the stage (+ give_up event)."""
    from sparkdl_tpu.runner import metrics
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan, install
    from sparkdl_tpu.runner.failures import (ScoringStageError,
                                             classify_exception)
    install(FaultPlan([Fault("dispatch", "preempt", prob=1.0, once=False)]))
    r = runtime.BatchRunner(lambda b: b * 2.0, 4)
    with pytest.raises(ScoringStageError, match="stage 'dispatch'") as ei:
        list(r.run(iter([np.ones((4, 2), np.float32)])))
    assert ei.value.attempts == 1 + runtime.dispatch_retries_default()
    assert classify_exception(ei.value) == "retryable"
    evs = fast_backoff.tail()
    assert [e["name"] for e in evs].count("retry") == \
        runtime.dispatch_retries_default()
    assert any(e["name"] == "give_up" and e["stage"] == "dispatch"
               for e in evs)
    assert metrics.run_stats.dispatch_giveups == 1


@pytest.mark.chaos
def test_dispatch_fatal_fault_not_retried(fast_backoff):
    from sparkdl_tpu.runner import metrics
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan, install
    from sparkdl_tpu.runner.failures import (ScoringStageError,
                                             classify_exception)
    install(FaultPlan([Fault("dispatch", "fatal", prob=1.0, once=False)]))
    r = runtime.BatchRunner(lambda b: b * 2.0, 4)
    with pytest.raises(ScoringStageError) as ei:
        list(r.run(iter([np.ones((4, 2), np.float32)])))
    assert ei.value.attempts == 1  # fatal: no retry burned
    assert classify_exception(ei.value) == "fatal"
    assert metrics.run_stats.dispatch_retries == 0


def test_retries_disabled_restores_lean_path(fast_backoff, monkeypatch):
    """SPARKDL_DISPATCH_RETRIES=0: no host copy pinned, first error
    raises as the classified stage error with attempts=1."""
    monkeypatch.setenv("SPARKDL_DISPATCH_RETRIES", "0")
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan, install
    from sparkdl_tpu.runner.failures import ScoringStageError
    install(FaultPlan([Fault("dispatch", "preempt", prob=1.0, once=True)]))
    r = runtime.BatchRunner(lambda b: b * 2.0, 4)
    with pytest.raises(ScoringStageError, match="1 attempt"):
        list(r.run(iter([np.ones((4, 2), np.float32)])))


def test_stall_watchdog_names_the_stage(fast_backoff, monkeypatch):
    """No progress for SPARKDL_DISPATCH_TIMEOUT_S -> a classified
    ScoringStallError naming the stage, not a silent hang. (On the
    synchronous CPU backend the hang blocks dispatch; on TPU it would
    surface at fetch — the watchdog covers both.)"""
    import time as time_mod
    from sparkdl_tpu.runner.failures import (ScoringStallError,
                                             classify_exception)
    r = runtime.BatchRunner(lambda b: b * 2.0, 4)
    # warm the compile OUTSIDE the watchdog window: the timeout must
    # bound steady-state progress, not the first-call XLA compile
    list(r.run(iter([np.ones((4, 2), np.float32)])))
    monkeypatch.setenv("SPARKDL_DISPATCH_TIMEOUT_S", "0.4")

    def wedge(b):
        def cb(x):
            time_mod.sleep(2.0)
            return np.asarray(x)
        return jax.pure_callback(cb, jax.ShapeDtypeStruct(b.shape, b.dtype),
                                 b)

    r2 = runtime.BatchRunner(wedge, 4)
    t0 = time_mod.perf_counter()
    with pytest.raises(ScoringStallError, match="no progress") as ei:
        list(r2.run(iter([np.ones((4, 2), np.float32)])))
    assert ei.value.stage in ("dispatch", "fetch")
    assert classify_exception(ei.value) == "retryable"
    assert time_mod.perf_counter() - t0 < 1.9  # did NOT wait out the hang
    assert any(e["name"] == "give_up" and e.get("stalled")
               for e in fast_backoff.tail())
