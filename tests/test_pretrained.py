"""Foreign-checkpoint import tests (SURVEY.md §7 hard-part #4).

Checkpoints are generated locally with the installed ``transformers``
(torch) and ``keras`` packages — real foreign layouts, zero egress — and
imports are verified by FORWARD-PASS EQUIVALENCE against the originating
implementation, not just shape checks.
"""

import numpy as np
import pytest

import jax

from sparkdl_tpu.models import pretrained
from sparkdl_tpu.models.pretrained import (CheckpointMismatch,
                                           import_hf_bert, import_hf_llama,
                                           load_pretrained,
                                           merge_into_template,
                                           read_keras_h5)


def _torch_state_to_safetensors(model, path):
    from safetensors.torch import save_file
    state = {k: v.contiguous() for k, v in model.state_dict().items()}
    save_file(state, str(path))


# ---------------------------------------------------------------------------
# HF Llama
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def torch_mod():
    return pytest.importorskip("torch")


def test_import_hf_llama_forward_equivalence(tmp_path, torch_mod):
    torch = torch_mod
    tr = pytest.importorskip("transformers")
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny()
    hf_cfg = tr.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        intermediate_size=cfg.intermediate_size,
        rope_theta=cfg.rope_theta, rms_norm_eps=cfg.rms_norm_eps,
        max_position_embeddings=64, attention_bias=False,
        mlp_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = tr.LlamaForCausalLM(hf_cfg).eval()
    f = tmp_path / "llama_hf.safetensors"
    _torch_state_to_safetensors(hf, f)

    variables = import_hf_llama(str(f), cfg)

    ids = np.array([[3, 14, 15, 92, 6], [2, 7, 1, 8, 2]], np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(LlamaModel(cfg).apply(variables, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_import_hf_llama_tied_embeddings_and_errors(torch_mod):
    from sparkdl_tpu.models.llama import LlamaConfig
    cfg = LlamaConfig.tiny()

    def full_state():
        rng = np.random.RandomState(0)  # deterministic per call
        hs, hd = cfg.hidden_size, cfg.head_dim
        s = {"model.embed_tokens.weight":
             rng.randn(cfg.vocab_size, hs).astype(np.float32),
             "model.norm.weight": np.ones(hs, np.float32)}
        for i in range(cfg.num_layers):
            p = f"model.layers.{i}."
            s[p + "self_attn.q_proj.weight"] = rng.randn(
                cfg.num_heads * hd, hs).astype(np.float32)
            s[p + "self_attn.k_proj.weight"] = rng.randn(
                cfg.num_kv_heads * hd, hs).astype(np.float32)
            s[p + "self_attn.v_proj.weight"] = rng.randn(
                cfg.num_kv_heads * hd, hs).astype(np.float32)
            s[p + "self_attn.o_proj.weight"] = rng.randn(
                hs, cfg.num_heads * hd).astype(np.float32)
            s[p + "mlp.gate_proj.weight"] = rng.randn(
                cfg.intermediate_size, hs).astype(np.float32)
            s[p + "mlp.up_proj.weight"] = rng.randn(
                cfg.intermediate_size, hs).astype(np.float32)
            s[p + "mlp.down_proj.weight"] = rng.randn(
                hs, cfg.intermediate_size).astype(np.float32)
            s[p + "input_layernorm.weight"] = np.ones(hs, np.float32)
            s[p + "post_attention_layernorm.weight"] = np.ones(
                hs, np.float32)
        return s

    # tied embeddings: no lm_head.weight → embedding transpose
    state = full_state()
    v = import_hf_llama(state, cfg)
    np.testing.assert_array_equal(
        v["params"]["lm_head"]["kernel"],
        full_state()["model.embed_tokens.weight"].T)

    # missing key → clear error
    state = full_state()
    del state["model.layers.0.self_attn.q_proj.weight"]
    with pytest.raises(CheckpointMismatch, match="missing"):
        import_hf_llama(state, cfg)

    # wrong shape → clear error
    state = full_state()
    state["model.layers.0.self_attn.q_proj.weight"] = np.zeros(
        (7, 7), np.float32)
    with pytest.raises(CheckpointMismatch, match="shape"):
        import_hf_llama(state, cfg)

    # extra keys → config mismatch error
    state = full_state()
    state["model.layers.9.self_attn.q_proj.weight"] = np.zeros(
        (1,), np.float32)
    with pytest.raises(CheckpointMismatch, match="unconsumed"):
        import_hf_llama(state, cfg)


def test_imported_llama_works_with_lora_template(torch_mod):
    """Base HF weights + LoRA-enabled model: merge keeps the freshly-init
    adapters and overlays everything else."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
    cfg = LlamaConfig.tiny(lora_rank=2)
    base_cfg = LlamaConfig.tiny()
    rng = np.random.RandomState(1)
    hs, hd = base_cfg.hidden_size, base_cfg.head_dim
    state = {"embed_tokens.weight":
             rng.randn(base_cfg.vocab_size, hs).astype(np.float32),
             "norm.weight": np.ones(hs, np.float32),
             "lm_head.weight": rng.randn(
                 base_cfg.vocab_size, hs).astype(np.float32)}
    for i in range(base_cfg.num_layers):
        p = f"layers.{i}."
        state[p + "self_attn.q_proj.weight"] = rng.randn(
            base_cfg.num_heads * hd, hs).astype(np.float32)
        state[p + "self_attn.k_proj.weight"] = rng.randn(
            base_cfg.num_kv_heads * hd, hs).astype(np.float32)
        state[p + "self_attn.v_proj.weight"] = rng.randn(
            base_cfg.num_kv_heads * hd, hs).astype(np.float32)
        state[p + "self_attn.o_proj.weight"] = rng.randn(
            hs, base_cfg.num_heads * hd).astype(np.float32)
        state[p + "mlp.gate_proj.weight"] = rng.randn(
            base_cfg.intermediate_size, hs).astype(np.float32)
        state[p + "mlp.up_proj.weight"] = rng.randn(
            base_cfg.intermediate_size, hs).astype(np.float32)
        state[p + "mlp.down_proj.weight"] = rng.randn(
            hs, base_cfg.intermediate_size).astype(np.float32)
        state[p + "input_layernorm.weight"] = np.ones(hs, np.float32)
        state[p + "post_attention_layernorm.weight"] = np.ones(
            hs, np.float32)

    imported = import_hf_llama(state, base_cfg)
    model = LlamaModel(cfg)
    template = model.init(jax.random.PRNGKey(0),
                          np.zeros((1, 4), np.int32))
    merged = merge_into_template(imported, template)
    # adapters exist and lora_b is zero-init → forward == base forward
    q = merged["params"]["layer_0"]["attn"]["q_proj"]
    assert "lora_a" in q and "lora_b" in q
    ids = np.array([[1, 2, 3, 4]], np.int32)
    from sparkdl_tpu.models.llama import LlamaModel as LM
    base_logits = LM(base_cfg).apply(imported, ids)
    lora_logits = model.apply(merged, ids)
    np.testing.assert_allclose(np.asarray(lora_logits),
                               np.asarray(base_logits), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# HF BERT
# ---------------------------------------------------------------------------

def test_import_hf_bert_forward_equivalence(tmp_path, torch_mod):
    torch = torch_mod
    tr = pytest.importorskip("transformers")
    from sparkdl_tpu.models.bert import (BertConfig,
                                         BertForSequenceClassification)

    cfg = BertConfig.tiny()
    hf_cfg = tr.BertConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_position_embeddings,
        type_vocab_size=cfg.type_vocab_size,
        layer_norm_eps=cfg.layer_norm_eps, num_labels=3,
        hidden_act="gelu")
    torch.manual_seed(0)
    hf = tr.BertForSequenceClassification(hf_cfg).eval()
    f = tmp_path / "bert_hf.safetensors"
    _torch_state_to_safetensors(hf, f)

    variables = import_hf_bert(str(f), cfg, num_classes=3)

    ids = np.array([[2, 45, 99, 31, 0, 0], [7, 1, 22, 90, 41, 3]], np.int32)
    mask = np.array([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids, dtype=torch.long),
                  attention_mask=torch.tensor(mask)).logits.numpy()
    model = BertForSequenceClassification(cfg, num_classes=3)
    got = np.asarray(model.apply(variables, ids, mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_import_hf_bert_encoder_only_and_missing_classifier(torch_mod):
    torch = torch_mod
    tr = pytest.importorskip("transformers")
    from sparkdl_tpu.models.bert import BertConfig, BertEncoder

    cfg = BertConfig.tiny()
    hf_cfg = tr.BertConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_position_embeddings,
        type_vocab_size=cfg.type_vocab_size,
        layer_norm_eps=cfg.layer_norm_eps)
    torch.manual_seed(1)
    hf = tr.BertModel(hf_cfg).eval()
    state = {k: v.numpy() for k, v in hf.state_dict().items()}

    variables = import_hf_bert(state, cfg)  # bare-encoder keys (no "bert.")
    ids = np.array([[5, 9, 17, 2]], np.int32)
    with torch.no_grad():
        out = hf(torch.tensor(ids, dtype=torch.long))
    seq, pooled = BertEncoder(cfg).apply(variables, ids)
    np.testing.assert_allclose(np.asarray(seq),
                               out.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled),
                               out.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-4)

    # classification import from an encoder-only checkpoint: zero head
    v2 = import_hf_bert(state, cfg, num_classes=4)
    assert v2["params"]["classifier"]["kernel"].shape == (cfg.hidden_size, 4)
    np.testing.assert_array_equal(v2["params"]["classifier"]["kernel"], 0.0)


# ---------------------------------------------------------------------------
# Keras .h5
# ---------------------------------------------------------------------------

def _keras():
    keras = pytest.importorskip("keras")
    if keras.backend.backend() != "jax":
        pytest.skip("keras not on jax backend")
    return keras


@pytest.mark.slow
def test_import_keras_resnet50_forward_equivalence(tmp_path):
    keras = _keras()
    from sparkdl_tpu.models import resnet

    km = keras.applications.ResNet50(weights=None,
                                     classifier_activation=None)
    f = str(tmp_path / "r50.h5")
    km.save(f)  # legacy whole-model HDF5: real layer names survive

    template = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: resnet.ResNet50(num_classes=1000).init(
            jax.random.PRNGKey(0), np.zeros((1, 224, 224, 3), np.float32),
            train=False)))
    variables = load_pretrained("ResNet50", f, template=template)

    x = np.random.RandomState(0).uniform(
        -2, 2, (2, 224, 224, 3)).astype(np.float32)
    want = np.asarray(km(x, training=False))
    # keras-applications ResNet is v1: stride on the first 1x1
    mine = resnet.ResNet50(num_classes=1000, stride_on_3x3=False)
    got = np.asarray(mine.apply(variables, x, train=False))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_import_keras_inceptionv3_forward_equivalence(tmp_path):
    keras = _keras()
    from sparkdl_tpu.models import inception

    km = keras.applications.InceptionV3(weights=None,
                                        classifier_activation=None)
    f = str(tmp_path / "iv3.h5")
    km.save(f)

    template = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: inception.InceptionV3(num_classes=1000).init(
            jax.random.PRNGKey(0), np.zeros((1, 299, 299, 3), np.float32),
            train=False)))
    variables = load_pretrained("InceptionV3", f, template=template)

    x = np.random.RandomState(1).uniform(
        -1, 1, (1, 299, 299, 3)).astype(np.float32)
    want = np.asarray(km(x, training=False))
    got = np.asarray(inception.InceptionV3(num_classes=1000).apply(
        variables, x, train=False))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_read_keras_h5_legacy_format_and_vgg_mapping(tmp_path):
    """Hand-built legacy-topological .h5 (the published keras-applications
    layout, ':0'-suffixed weight names included) → name-mapped VGG import."""
    import h5py
    rng = np.random.RandomState(0)
    tensors = {
        "block1_conv1": [rng.randn(3, 3, 3, 8).astype(np.float32),
                         rng.randn(8).astype(np.float32)],
        "fc1": [rng.randn(32, 16).astype(np.float32),
                rng.randn(16).astype(np.float32)],
        "predictions": [rng.randn(16, 4).astype(np.float32),
                        rng.randn(4).astype(np.float32)],
    }
    f = str(tmp_path / "legacy_vgg.h5")
    with h5py.File(f, "w") as h:
        h.attrs["layer_names"] = np.array(
            [k.encode() for k in tensors] + [b"flatten"])
        h.create_group("flatten").attrs["weight_names"] = np.array([])
        for name, (kernel, bias) in tensors.items():
            g = h.create_group(name)
            g.attrs["weight_names"] = np.array(
                [f"{name}/kernel:0".encode(), f"{name}/bias:0".encode()])
            g.create_dataset(f"{name}/kernel:0", data=kernel)
            g.create_dataset(f"{name}/bias:0", data=bias)

    layers = read_keras_h5(f)
    assert set(layers) == set(tensors)
    np.testing.assert_array_equal(layers["fc1"][1], tensors["fc1"][1])

    template = {"params": {
        "block1_conv1": {"kernel": np.zeros((3, 3, 3, 8), np.float32),
                         "bias": np.zeros(8, np.float32)},
        "fc1": {"kernel": np.zeros((32, 16), np.float32),
                "bias": np.zeros(16, np.float32)},
        "head": {"kernel": np.zeros((16, 4), np.float32),
                 "bias": np.zeros(4, np.float32)},
    }}
    out = pretrained.import_keras_vgg(f, template)
    np.testing.assert_array_equal(out["params"]["head"]["kernel"],
                                  tensors["predictions"][0])

    # shape mismatch → clear error
    template["params"]["fc1"]["kernel"] = np.zeros((9, 9), np.float32)
    with pytest.raises(CheckpointMismatch):
        pretrained.import_keras_vgg(f, template)


@pytest.mark.slow
def test_featurizer_with_keras_h5_weights(tmp_path):
    """End-to-end BASELINE config-1 shape: DeepImageFeaturizer(weightsPath=
    keras .h5) runs the imported weights with keras-v1 semantics and matches
    the originating keras model's bottleneck features."""
    keras = _keras()
    import pyarrow as pa
    import sparkdl_tpu as sdl
    from sparkdl_tpu.image import imageIO

    km = keras.applications.ResNet50(weights=None)
    f = str(tmp_path / "r50.h5")
    km.save(f)
    feat_keras = keras.Model(km.input, km.layers[-2].output)  # avg_pool

    rng = np.random.RandomState(0)
    imgs = [rng.randint(0, 256, (224, 224, 3)).astype(np.uint8)
            for _ in range(3)]  # RGB
    # structs store BGR at rest (OpenCV convention) — flip before storing
    structs = [imageIO.imageArrayToStruct(im[:, :, ::-1]) for im in imgs]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}))

    feat = sdl.DeepImageFeaturizer(inputCol="image", outputCol="features",
                                   modelName="ResNet50", batchSize=4,
                                   weightsPath=f)
    got = np.stack([np.asarray(r.features, np.float32)
                    for r in feat.transform(df).collect()])

    from sparkdl_tpu.models.registry import preprocess_caffe
    x = np.stack([im.astype(np.float32) for im in imgs])
    want = np.asarray(feat_keras(np.asarray(preprocess_caffe(x)),
                                 training=False))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_import_keras_xception_forward_equivalence(tmp_path):
    keras = _keras()
    from sparkdl_tpu.models import xception

    km = keras.applications.Xception(weights=None,
                                     classifier_activation=None)
    f = str(tmp_path / "xc.h5")
    km.save(f)

    template = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: xception.Xception(num_classes=1000).init(
            jax.random.PRNGKey(0), np.zeros((1, 299, 299, 3), np.float32),
            train=False)))
    variables = load_pretrained("Xception", f, template=template)

    x = np.random.RandomState(2).uniform(
        -1, 1, (1, 299, 299, 3)).astype(np.float32)
    want = np.asarray(km(x, training=False))
    got = np.asarray(xception.Xception(num_classes=1000).apply(
        variables, x, train=False))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
