"""Pipeline/Transformer/Estimator contract + persistence tests."""

import numpy as np

from sparkdl_tpu.core.frame import DataFrame
from sparkdl_tpu.core.params import (HasInputCol, HasOutputCol, Param, Params,
                                     TypeConverters, keyword_only)
from sparkdl_tpu.core.pipeline import (Estimator, MLWritable, Model, Pipeline,
                                       PipelineModel, Transformer)


class AddConst(Transformer, HasInputCol, HasOutputCol):
    amount = Param(Params, "amount", "value to add", TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, amount=None):
        super().__init__()
        self._setDefault(amount=1.0)
        self._set(**self._input_kwargs)

    def _transform(self, dataset):
        a = self.getOrDefault(self.amount)
        return dataset.withColumnBatch(
            self.getOutputCol(), lambda x: np.asarray(x, dtype=np.float64) + a,
            inputCols=[self.getInputCol()])


class MeanModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, mean=0.0, inputCol=None, outputCol=None):
        super().__init__()
        self.mean = mean
        self._set(inputCol=inputCol, outputCol=outputCol)

    def _transform(self, dataset):
        return dataset.withColumnBatch(
            self.getOutputCol(),
            lambda x: np.asarray(x, dtype=np.float64) - self.mean,
            inputCols=[self.getInputCol()])

    def _save_payload(self, path):
        import json, os
        with open(os.path.join(path, "payload.json"), "w") as f:
            json.dump({"mean": self.mean}, f)

    def _load_payload(self, path, meta):
        import json, os
        with open(os.path.join(path, "payload.json")) as f:
            self.mean = json.load(f)["mean"]


class Center(Estimator, HasInputCol, HasOutputCol):
    @keyword_only
    def __init__(self, inputCol=None, outputCol=None):
        super().__init__()
        self._set(**self._input_kwargs)

    def _fit(self, dataset):
        vals = np.asarray([r[self.getInputCol()] for r in dataset.collect()])
        return MeanModel(float(vals.mean()), self.getInputCol(),
                         self.getOutputCol())


def data():
    return DataFrame.fromPydict({"v": [1.0, 2.0, 3.0, 4.0]}, numPartitions=2)


def test_transform_with_param_override():
    t = AddConst(inputCol="v", outputCol="o", amount=2.0)
    out = t.transform(data())
    assert [r.o for r in out.collect()] == [3.0, 4.0, 5.0, 6.0]
    out2 = t.transform(data(), {t.amount: 10.0})
    assert [r.o for r in out2.collect()] == [11.0, 12.0, 13.0, 14.0]
    assert t.getOrDefault("amount") == 2.0  # original untouched


def test_estimator_fit_and_fit_multiple():
    est = Center(inputCol="v", outputCol="c")
    model = est.fit(data())
    assert model.mean == 2.5
    out = model.transform(data())
    assert [r.c for r in out.collect()] == [-1.5, -0.5, 0.5, 1.5]

    t = AddConst(inputCol="v", outputCol="o")
    maps = [{t.amount: 1.0}, {t.amount: 2.0}]

    class AmountEst(Estimator):
        def __init__(self):
            super().__init__()
            self.amount = Param(self, "amount", "", TypeConverters.toFloat)
            self._setDefault(amount=0.0)

        def _fit(self, dataset):
            return MeanModel(self.getOrDefault("amount"), "v", "o")

    e = AmountEst()
    results = dict(e.fitMultiple(data(), [{e.amount: 5.0}, {e.amount: 7.0}]))
    assert results[0].mean == 5.0 and results[1].mean == 7.0
    models = e.fit(data(), [{e.amount: 1.0}, {e.amount: 2.0}])
    assert sorted(m.mean for m in models) == [1.0, 2.0]


def test_pipeline_fit_transform():
    pipe = Pipeline(stages=[
        AddConst(inputCol="v", outputCol="a", amount=1.0),
        Center(inputCol="a", outputCol="c"),
    ])
    pm = pipe.fit(data())
    assert isinstance(pm, PipelineModel)
    out = pm.transform(data())
    assert [r.c for r in out.collect()] == [-1.5, -0.5, 0.5, 1.5]


def test_pipeline_model_persistence(tmp_path):
    pipe = Pipeline(stages=[
        AddConst(inputCol="v", outputCol="a", amount=1.0),
        Center(inputCol="a", outputCol="c"),
    ])
    pm = pipe.fit(data())
    p = str(tmp_path / "pm")
    pm.save(p)
    loaded = MLWritable.load(p)
    assert isinstance(loaded, PipelineModel)
    out = loaded.transform(data())
    assert [r.c for r in out.collect()] == [-1.5, -0.5, 0.5, 1.5]
    assert loaded.uid == pm.uid
    assert loaded.stages[1].mean == 3.5


def test_transformer_persistence_roundtrip(tmp_path):
    t = AddConst(inputCol="v", outputCol="o", amount=4.0)
    p = str(tmp_path / "t")
    t.save(p)
    loaded = MLWritable.load(p)
    assert loaded.getOrDefault("amount") == 4.0
    assert loaded.getInputCol() == "v"
    out = loaded.transform(data())
    assert [r.o for r in out.collect()] == [5.0, 6.0, 7.0, 8.0]


def test_pipeline_estimator_persistence(tmp_path):
    pipe = Pipeline(stages=[AddConst(inputCol="v", outputCol="a", amount=1.0)])
    p = str(tmp_path / "pipe")
    pipe.save(p)
    loaded = MLWritable.load(p)
    assert isinstance(loaded, Pipeline)
    assert len(loaded.getStages()) == 1
    pm = loaded.fit(data())
    assert [r.a for r in pm.transform(data()).collect()] == [2.0, 3.0, 4.0, 5.0]


def test_fit_empty_param_maps():
    class E(Estimator):
        def _fit(self, dataset):
            return 1

    assert E().fit(data(), []) == []


def test_abstract_stages_not_instantiable():
    import pytest
    with pytest.raises(TypeError):
        Transformer()
    with pytest.raises(TypeError):
        Estimator()


class WithFn(Transformer, HasInputCol):
    fn = Param(Params, "fn", "a callable", TypeConverters.toCallable)

    def _transform(self, dataset):
        return dataset


def test_load_fails_loudly_on_unrestored_payload_params(tmp_path):
    import pytest

    t = WithFn()
    t._set(fn=lambda x: x)
    p = str(tmp_path / "fn")
    t.save(p)
    with pytest.raises(ValueError, match="fn"):
        MLWritable.load(p)


def test_pipeline_propagates_stage_params():
    # Spark contract: fit(df, params={stage.param: v}) reaches the stage.
    add = AddConst(inputCol="v", outputCol="a", amount=1.0)
    pipe = Pipeline(stages=[add])
    pm = pipe.fit(data(), params={add.amount: 10.0})
    assert [r.a for r in pm.transform(data()).collect()] == \
        [11.0, 12.0, 13.0, 14.0]
    assert add.getOrDefault("amount") == 1.0  # original untouched

    # PipelineModel.transform(df, params={stage.param: v}) too
    pm2 = Pipeline(stages=[add]).fit(data())
    out = pm2.transform(data(), params={add.amount: 5.0})
    assert [r.a for r in out.collect()] == [6.0, 7.0, 8.0, 9.0]


def test_copy_ignores_foreign_params():
    a, b = AddConst(), AddConst()
    c = a.copy({b.amount: 9.0})  # foreign param silently ignored (Spark)
    assert not c.isSet(c.amount)
