"""Paged KV cache (ISSUE 11): block-table serving memory.

Three layers, leanest first: jax-free allocator/radix invariants (the
acceptance pins — no double-free, refcounted copy-on-write after a
radix graft, exhaustion backpressures admission without evicting
RUNNING requests), jax-free paged-engine scheduling over the
``StubBackend`` mirror (admission block gate, multi-chunk prefill
budgets, preemption-resume, pointer-graft sharing), then ONE lean
CPU-llama class proving greedy token identity through paging +
multi-chunk budgets + radix grafts with zero decode re-traces, and the
shared head resident as one physical block set.
"""

import numpy as np
import pytest

from sparkdl_tpu.serving import (BlockAllocator, BlockError,
                                 BlockExhausted, GenerationEngine,
                                 PagedBlockManager, RadixPrefixCache,
                                 RequestRejected, StubBackend)

# ---------------------------------------------------------------------------
# allocator invariants (jax-free)
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_alloc_free_cycle_and_trash_pinned(self):
        a = BlockAllocator(8)  # block 0 = trash, 7 usable
        assert a.usable_blocks == 7 and a.free_count() == 7
        got = a.allocate(3)
        assert len(got) == 3 and 0 not in got  # trash never handed out
        assert a.used_count() == 3
        for b in got:
            assert a.deref(b) == 0
        assert a.free_count() == 7 and a.stats()["frees"] == 3

    def test_double_free_and_bad_refs_raise(self):
        a = BlockAllocator(4)
        (b,) = a.allocate(1)
        a.deref(b)
        with pytest.raises(BlockError, match="double free"):
            a.deref(b)
        with pytest.raises(BlockError, match="trash"):
            a.deref(0)
        with pytest.raises(BlockError, match="unallocated"):
            a.ref(b)  # freed — re-refing it would resurrect a dangler
        with pytest.raises(BlockError, match="invalid"):
            a.deref(99)

    def test_refcounts_shared_and_stats(self):
        a = BlockAllocator(6)
        b1, b2 = a.allocate(2)
        assert a.ref(b1) == 2 and a.is_shared(b1)
        assert not a.is_shared(b2)
        st = a.stats()
        assert st["blocks_used"] == 2 and st["blocks_shared"] == 1
        assert st["shared_frac"] == 0.5
        assert st["peak_utilization"] == pytest.approx(2 / 5)
        a.deref(b1)
        assert not a.is_shared(b1) and a.used_count() == 2  # still held

    def test_exhaustion_returns_none_and_reclaim_hook(self):
        a = BlockAllocator(4)  # 3 usable
        held = a.allocate(3)
        assert a.allocate(1) is None
        assert a.stats()["failed_allocs"] == 1
        calls = []

        def reclaim(k):
            calls.append(k)
            a.deref(held.pop())  # free one on demand
            return 1

        got = a.allocate(1, reclaim=reclaim)
        assert len(got) == 1 and calls == [1]

    def test_alloc_latency_ledger_drains(self):
        a = BlockAllocator(4)
        a.allocate(2)
        samples = a.drain_alloc_samples()
        assert len(samples) == 1 and samples[0] >= 0.0
        assert a.drain_alloc_samples() == []


# ---------------------------------------------------------------------------
# radix trie (jax-free)
# ---------------------------------------------------------------------------


def _radix(pool=32, bs=4):
    a = BlockAllocator(pool)
    return a, RadixPrefixCache(a, bs)


class TestRadixPrefixCache:
    def test_insert_lookup_full_blocks_only(self):
        a, r = _radix()
        blocks = a.allocate(3)
        prompt = list(range(10))  # 2 full blocks of 4; 2-token tail
        assert r.insert(prompt, blocks) == 2  # tail block never cached
        assert len(r) == 2
        assert r.lookup(prompt) == blocks[:2]
        assert r.lookup(list(range(8)) + [99]) == blocks[:2]  # head only
        assert r.lookup([7, 7, 7, 7]) == []
        # the trie holds one ref per cached block
        assert a.refcount(blocks[0]) == 2 and a.refcount(blocks[2]) == 1

    def test_duplicate_run_keeps_existing_block(self):
        a, r = _radix()
        first = a.allocate(1)
        second = a.allocate(1)
        r.insert([1, 2, 3, 4], first)
        assert r.insert([1, 2, 3, 4], second) == 0  # run already cached
        assert r.lookup([1, 2, 3, 4]) == first
        assert a.refcount(second[0]) == 1  # committer keeps its copy

    def test_evict_lru_leaf_first_and_only_unreferenced(self):
        a, r = _radix()
        chain = a.allocate(2)          # [1,2,3,4] -> [5,6,7,8]
        other = a.allocate(1)          # [9,9,9,9]
        r.insert([1, 2, 3, 4, 5, 6, 7, 8], chain)
        r.insert([9, 9, 9, 9], other)
        for b in chain + other:
            a.deref(b)                 # committers release: trie-only now
        r.use([9, 9, 9, 9], 1, 4)      # touch -> chain tail is LRU leaf
        assert r.evict(1) == 1         # the chain LEAF [5..8], never the
        assert r.lookup([1, 2, 3, 4]) == chain[:1]  # still-parented head
        # a grafted (refcount 2) block is untouchable
        a.ref(other[0])
        assert r.evict(5) == 1  # only the chain head was evictable
        assert r.lookup([9, 9, 9, 9]) == other
        st = r.stats()
        assert st["evictions"] == 2 and st["hits"] == 1

    def test_clear_drops_trie_refs_only(self):
        a, r = _radix()
        blocks = a.allocate(1)
        r.insert([1, 2, 3, 4], blocks)
        r.clear()
        assert len(r) == 0
        assert a.refcount(blocks[0]) == 1  # committer's ref survives


# ---------------------------------------------------------------------------
# manager: reservation / CoW / release (jax-free)
# ---------------------------------------------------------------------------


class TestPagedBlockManager:
    def test_reserve_graft_then_private_and_release(self):
        m = PagedBlockManager(2, 64, 4, 16)
        assert m.reserve_prompt(0, list(range(10)), chunk=4) == 0  # cold
        assert len(m.slot_blocks[0]) == 4  # ceil(12/4)=3 prompt + 1
        m.commit(0, list(range(10)))       # 2 full blocks cached
        m.release(0)
        assert m.allocator.used_count() == 2  # trie keeps the 2 cached
        # warm: same head grafts 2 blocks (pointers, shared), tail private
        reuse = m.reserve_prompt(1, list(range(10)), chunk=4)
        assert reuse == 8
        assert m.slot_blocks[1][:2] == m.radix.lookup(list(range(10)))
        assert m.allocator.is_shared(m.slot_blocks[1][0])
        m.release(1)
        # release is idempotent (the block list empties), and the
        # trie's refs survive: only its 2 cached blocks stay resident
        m.release(1)
        assert m.allocator.used_count() == 2

    def test_reserve_rollback_on_exhaustion_leaks_nothing(self):
        m = PagedBlockManager(2, 64, 4, 5)  # 4 usable blocks
        with pytest.raises(BlockExhausted):
            m.reserve_prompt(0, list(range(30)), chunk=4)  # needs 9
        assert m.slot_blocks[0] == []
        assert m.allocator.used_count() == 0  # full rollback
        # and a graft that precedes the failed allocation rolls back too
        m2 = PagedBlockManager(2, 64, 4, 6)  # 5 usable
        m2.reserve_prompt(0, list(range(8)), chunk=4)   # 2+1 = 3 used
        m2.commit(0, list(range(8)))
        m2.release(0)                                   # trie keeps 2
        with pytest.raises(BlockExhausted):
            # grafts 2, then needs ceil(20/4)-2+1 = 4 privates; free = 3
            m2.reserve_prompt(1, list(range(8)) + list(range(50, 62)),
                              chunk=4)
        assert m2.slot_blocks[1] == []
        assert m2.allocator.used_count() == 2  # only the trie's blocks

    def test_cow_on_shared_block_write(self):
        copies = []
        m = PagedBlockManager(2, 64, 4, 16,
                              copy_block=lambda s, d: copies.append(
                                  (s, d)))
        m.reserve_prompt(0, list(range(8)), chunk=4)
        m.commit(0, list(range(8)))
        m.release(0)
        m.reserve_prompt(1, list(range(8)), chunk=4)  # grafts block 0-1?
        # reuse = usable_reuse(8, 8, 4) = 4 -> one grafted block
        shared = m.slot_blocks[1][0]
        assert m.allocator.is_shared(shared)
        # a write into the shared block triggers copy-on-write: fresh
        # private block, contents copied, old ref dropped — the OTHER
        # holder (the trie) keeps reading the original
        assert m.ensure_block_for(1, 0) is True
        assert m.slot_blocks[1][0] != shared
        assert copies == [(shared, m.slot_blocks[1][0])]
        assert m.allocator.refcount(shared) == 1  # trie's ref only
        assert m.allocator.stats()["cow_blocks"] == 1

    def test_decode_growth_and_stall(self):
        m = PagedBlockManager(1, 64, 4, 4)  # 3 usable
        m.reserve_prompt(0, [1, 2, 3], chunk=4)  # 1 prompt + 1 decode
        assert m.ensure_block_for(0, 7) is True   # within reservation
        assert m.ensure_block_for(0, 8) is True   # growth: 3rd block
        assert m.ensure_block_for(0, 12) is False  # pool dry: stall
        assert m.ensure_block_for(0, 999) is False  # beyond the row


# ---------------------------------------------------------------------------
# paged engine scheduling over the stub mirror (jax-free)
# ---------------------------------------------------------------------------


def _paged_stub(slots=4, max_len=64, *, block_size=4, pool_blocks=80,
                **kw):
    return StubBackend(slots, max_len, vocab_size=100,
                       block_size=block_size, pool_blocks=pool_blocks,
                       **kw)


class TestPagedStubEngine:
    def test_token_identity_and_pool_stats(self):
        def run(paged):
            be = _paged_stub() if paged else StubBackend(4, 64,
                                                         vocab_size=100)
            eng = GenerationEngine(be, prefill_chunk=4)
            rs = [eng.submit(list(range(b, b + 9)), max_new_tokens=5)
                  for b in (1, 20, 40, 60, 1)]
            eng.run_until_idle()
            return [r.result(1) for r in rs], eng.snapshot()

        toks_p, snap_p = run(True)
        toks_l, snap_l = run(False)
        assert toks_p == toks_l  # paging never changes the stream
        pool = snap_p["kv_pool"]
        assert snap_p["paged"] is True and pool["blocks_total"] == 79
        assert pool["peak_utilization"] > 0
        assert snap_p["prefix_cache"]["hits"] >= 1  # repeated prompt
        assert "kv_pool" not in snap_l

    def test_admission_gate_waits_never_evicts_running(self):
        # pool covers ~one request at a time: the second WAITS (counted)
        # and completes after the first retires — no quarantine, no
        # preemption, no crash (the ISSUE 11 backpressure acceptance)
        be = _paged_stub(slots=4, pool_blocks=9)  # 8 usable
        eng = GenerationEngine(be, prefill_chunk=4)
        rs = [eng.submit(list(range(1, 12)), max_new_tokens=4)
              for _ in range(4)]  # each needs ceil(12/4)+1 = 4 blocks
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["completed"] == 4
        assert snap["admission_block_waits"] > 0
        assert snap["quarantined"] == 0 and snap["preemptions"] == 0
        assert all(len(r.result(1)) == 4 for r in rs)
        assert be.allocator.used_count() == len(be.mgr.radix or [])

    def test_never_fits_rejected_at_the_door(self):
        be = _paged_stub(slots=2, pool_blocks=5)  # 4 usable
        eng = GenerationEngine(be, prefill_chunk=4)
        with pytest.raises(RequestRejected, match="never fit"):
            eng.submit(list(range(1, 13)), max_new_tokens=8)  # 6 blocks
        assert eng.snapshot()["rejected"] == 1

    def test_multi_chunk_budget_fills_multiple_slots_per_iteration(self):
        def chunks_after_one_step(budget):
            be = _paged_stub(slots=3, pool_blocks=80)
            eng = GenerationEngine(be, prefill_chunk=4,
                                   prefill_budget=budget)
            a = eng.submit(list(range(1, 9)), max_new_tokens=1)
            b = eng.submit(list(range(11, 19)), max_new_tokens=1)
            eng.step()
            n = eng.snapshot()["prefill_chunks"]
            eng.run_until_idle()
            assert a.result(1) and b.result(1)
            return n

        assert chunks_after_one_step(None) == 1   # PR 9 default pacing
        assert chunks_after_one_step(8) == 2      # 2 slots, 1 iteration

    def test_budget_drains_one_long_prompt_faster(self):
        be = _paged_stub(slots=2, max_len=128, pool_blocks=80)
        eng = GenerationEngine(be, prefill_chunk=4, prefill_budget=16)
        r = eng.submit(list(range(1, 17)), max_new_tokens=1)  # 4 chunks
        eng.step()
        assert eng.snapshot()["prefill_chunks"] == 4  # one iteration
        eng.run_until_idle()
        assert r.result(1)

    def test_preemption_breaks_total_stall_and_resumes(self):
        # each request alone fits (5 blocks); two concurrently demand 8
        # of 5 usable -> decode growth eventually stalls BOTH -> the
        # newest is preempted (requeued, blocks freed), the oldest
        # finishes, the victim resumes and completes its full length
        be = _paged_stub(slots=2, pool_blocks=6,
                         prefix_cache_bytes=0)  # 5 usable
        eng = GenerationEngine(be, prefill_chunk=4)
        a = eng.submit([1, 2, 3, 4], max_new_tokens=12)
        b = eng.submit([5, 6, 7, 8], max_new_tokens=12)
        eng.run_until_idle()
        snap = eng.snapshot()
        assert snap["completed"] == 2
        assert snap["preemptions"] >= 1
        assert snap["block_stall_events"] >= 1
        assert snap["quarantined"] == 0
        assert len(a.result(1)) == 12 and len(b.result(1)) == 12
        assert b.preemptions + a.preemptions == snap["preemptions"]
        assert be.allocator.used_count() == 0  # every block came home

    def test_resume_with_chunk_pad_past_max_len_is_clamped(self):
        """A preemption resume prefills prompt + generated tokens; when
        the chunk size does not divide max_len, the chunk-aligned
        served length can pad PAST the slot row (submit only aligned
        the original prompt). The reservation must clamp to max_blocks
        instead of overflowing the table, and the resumed request must
        still complete its full length."""
        be = _paged_stub(slots=2, max_len=20, block_size=4,
                         pool_blocks=12, prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=8)
        r = eng.submit(list(range(1, 11)), max_new_tokens=10)  # L+new=20
        for _ in range(8):  # 2 prefill iterations + 8 tokens
            eng.step()
        assert r.state == "running" and len(r.tokens) >= 8
        # force the corner directly: preempt, then resume — served is
        # now 18+ tokens, chunk-aligned 24 > max_len 20
        eng._preempt_newest([(r.slot, r)])
        assert r.state == "queued"
        eng.run_until_idle()
        assert len(r.result(1)) == 10
        assert eng.snapshot()["preemptions"] == 1
        assert be.allocator.used_count() == 0

    def test_resume_need_never_exceeds_submit_gate(self):
        """Review finding: chunk-aligning the resumed served prompt
        could inflate _blocks_needed past what submit gated (chunk 16,
        max_len 32, 7-usable pool: resume aligned to 32 -> 9 blocks),
        livelocking the queue head forever. Real rows only (pad writes
        go to the trash block): the resumed request must re-admit and
        finish."""
        be = _paged_stub(slots=2, max_len=32, block_size=4,
                         pool_blocks=8, prefix_cache_bytes=0)
        eng = GenerationEngine(be, prefill_chunk=16)
        r = eng.submit(list(range(1, 17)), max_new_tokens=8)
        for _ in range(3):
            eng.step()
        assert r.state == "running" and len(r.tokens) >= 1
        eng._preempt_newest([(r.slot, r)])  # served is 17+ tokens now
        eng.run_until_idle()
        assert len(r.result(1)) == 8
        assert eng.snapshot()["preemptions"] == 1
        assert be.allocator.used_count() == 0

    def test_blocking_resume_rebucket_clamps_to_max_len(self):
        """Review finding: a blocking-mode resume re-bucketed with
        bucket_length(served) (64 for 33 tokens), exceeding a
        non-power-of-two max_len (48) and quarantining a healthy
        request. The bucket must clamp to max_len - remaining and the
        request must complete its full length."""
        be = _paged_stub(slots=2, max_len=48, block_size=16,
                         pool_blocks=16, prefix_cache_bytes=0)
        eng = GenerationEngine(be, stall_free=False, min_bucket=16)
        r = eng.submit(list(range(1, 31)), max_new_tokens=4)  # bucket 32
        eng.step()
        assert r.state == "running"
        eng._preempt_newest([(r.slot, r)])  # served 31+ -> bucket_length 64
        eng.run_until_idle()
        assert len(r.result(1)) == 4  # completed, NOT quarantined
        assert eng.snapshot()["quarantined"] == 0
        assert be.allocator.used_count() == 0

    def test_shared_head_is_pointer_graft_not_copy(self):
        be = _paged_stub(slots=2, max_len=64, pool_blocks=40)
        eng = GenerationEngine(be, prefill_chunk=4)
        head = list(range(1, 9))  # 2 full blocks
        h1 = eng.submit(head + [70, 71], max_new_tokens=2)
        eng.run_until_idle()
        allocs_cold = be.allocator.stats()["allocs"]
        h2 = eng.submit(head + [80, 81, 82], max_new_tokens=2)
        eng.run_until_idle()
        assert h1.result(1) and h2.result(1)
        st = be.mgr.prefix_stats()
        assert st["hits"] == 1 and st["reused_tokens"] == 8
        # the graft allocated only the TAIL's blocks (2: tail + decode),
        # not the head's
        assert be.allocator.stats()["allocs"] - allocs_cold <= 2

    def test_blocking_mode_pages_too(self):
        """SPARKDL_SERVE_STALL_FREE=0 on a paged backend still pages:
        bucketed whole-prompt refills reserve bucket + 1 blocks, the
        stream matches the legacy engine, and release returns every
        block."""
        def run(paged):
            be = _paged_stub(slots=2, pool_blocks=40) if paged else \
                StubBackend(2, 64, vocab_size=100)
            eng = GenerationEngine(be, stall_free=False, min_bucket=8)
            rs = [eng.submit(list(range(b, b + 5)), max_new_tokens=3)
                  for b in (1, 30, 60)]
            eng.run_until_idle()
            return [r.result(1) for r in rs], be

        toks_p, be = run(True)
        toks_l, _ = run(False)
        assert toks_p == toks_l
        assert be.allocator.used_count() == 0  # all released

    def test_pool_gauges_and_alloc_histogram_reach_telemetry(self):
        from sparkdl_tpu.runner import telemetry
        telemetry.reset()
        telemetry.start()
        try:
            eng = GenerationEngine(_paged_stub(), prefill_chunk=4)
            eng.submit(list(range(1, 9)), max_new_tokens=3)
            eng.run_until_idle()
            snap = telemetry.registry().snapshot()
            assert "serving_kv_blocks_free" in snap["gauges"]
            assert "serving_kv_blocks_shared" in snap["gauges"]
            assert snap["histograms"]["serving_block_alloc_s"][
                "count"] >= 1
        finally:
            telemetry.reset()

    def test_engine_registers_nothing_when_plane_off(self):
        from sparkdl_tpu.runner import telemetry
        telemetry.reset()
        eng = GenerationEngine(_paged_stub(), prefill_chunk=4)
        eng.submit([1, 2], max_new_tokens=2)
        eng.run_until_idle()
        assert telemetry.registry().snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}

    def test_bottleneck_report_surfaces_pool_gauges(self, tmp_path,
                                                    capsys):
        """An HBM-bound engine must be attributable from the report:
        the gang-aggregated pool gauges print next to the stage table
        (in-process main(), per the tier-1 lean rule)."""
        import importlib.util
        import json
        import os
        snap = {"t": 1.0, "rank": 0, "elapsed_s": 1.0, "stages": {},
                "gauges": {"serving_kv_blocks_free":
                           {"value": 3.0, "max": 64.0},
                           "serving_kv_blocks_shared":
                           {"value": 12.0, "max": 17.0}}}
        (tmp_path / "metrics_rank0.json").write_text(json.dumps(snap))
        spec = importlib.util.spec_from_file_location(
            "bottleneck_report",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "bottleneck_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main([str(tmp_path / "no-events"), "--metrics-dir",
                       str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serving_kv_blocks_free: 3" in out
        assert "high-water 17" in out


# ---------------------------------------------------------------------------
# paged engine on CPU over the tiny model (lean: one compile set)
# ---------------------------------------------------------------------------


class TestPagedEngineOnCpu:
    def test_resume_pad_past_table_fast_twin(self):
        """Lean twin of the slow static-anchored test below (the tier-1
        budget rule): same contract — a resume whose chunk plan pads
        past the block table (served 18 with chunk 16 → aligned 32 >
        max_len 24) must route its pad writes to the trash block, never
        clamp them back over committed rows. Reference = the SAME
        engine config run without the preemption (whose generate()-
        identity the static-anchored tests pin), so the twin skips the
        two extra generate() programs; a 1-layer model (the clobber
        contract is per-layer-identical) halves the compile cost. The
        slow test keeps the static anchor on the full tiny model."""
        import dataclasses

        import jax

        from sparkdl_tpu.models import llama as L

        cfg = dataclasses.replace(L.LlamaConfig.tiny(), num_layers=1)
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(13)
        prompt = rng.randint(0, cfg.vocab_size, 16).tolist()

        def make_engine():
            return GenerationEngine.from_model(
                model, variables, num_slots=1, max_len=24, block_size=8,
                prefill_chunk=16, prefix_cache_mb=0)

        ref_eng = make_engine()  # no preemption: the clean stream
        ref_h = ref_eng.submit(prompt, max_new_tokens=8)
        ref_eng.run_until_idle()
        ref = ref_h.result(1)

        eng = make_engine()
        r = eng.submit(prompt, max_new_tokens=8)
        eng.step()  # chunk 1
        eng.step()  # finish + first tokens
        assert r.state == "running" and len(r.tokens) >= 2
        eng._preempt_newest([(r.slot, r)])  # served 18 -> aligned 32 > 24
        eng.run_until_idle()
        assert r.result(1) == ref
        assert eng.snapshot()["preemptions"] == 1

    @pytest.mark.slow
    def test_resume_pad_past_table_never_clobbers_committed_rows(self):
        """Review finding: a resume whose chunk plan pads past the
        block table used to CLAMP the out-of-range scatter onto the
        last live block, overwriting the served prompt's committed K/V
        (chunk 16, max_len 24, served 18 -> pad positions 24..31
        landed on rows 16..23). Pad writes must route to the trash
        block: the resumed request's greedy output stays bit-identical
        to static generate(). (Slow: the fast twin above pins the same
        contract engine-vs-engine; this keeps the static anchor.)"""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(13)
        prompt = rng.randint(0, cfg.vocab_size, 16).tolist()
        ids, lens = L.left_pad_prompts([prompt])
        ref = np.asarray(L.generate(model, variables, np.asarray(ids), 8,
                                    pad_lens=np.asarray(lens),
                                    pad_to=24))[0][16:].tolist()
        eng = GenerationEngine.from_model(
            model, variables, num_slots=1, max_len=24, block_size=8,
            prefill_chunk=16, prefix_cache_mb=0)
        r = eng.submit(prompt, max_new_tokens=8)
        eng.step()  # chunk 1
        eng.step()  # finish + first tokens
        assert r.state == "running" and len(r.tokens) >= 2
        eng._preempt_newest([(r.slot, r)])  # served 18 -> aligned 32 > 24
        eng.run_until_idle()
        assert r.result(1) == ref
        assert eng.snapshot()["preemptions"] == 1

    def test_paged_token_identity_radix_graft_and_cow(self):
        """Paged llama engine with a multi-chunk budget: mixed 1/2/3-
        chunk prompts must emit exactly the static generate() greedy
        tokens; a shared head must be ONE physical block set across two
        concurrently RUNNING slots (pointer graft); a forced write into
        the shared block must copy-on-write with bit-identical content;
        and the decode step must never re-trace through any of it."""
        import jax

        from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(7)
        max_len, new = 64, 6

        # every reference stream from ONE batched generate() call (one
        # prefill + one decode compile — the tier-1 lean rule)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 17)]  # 1-chunk and 3-chunk
        head = rng.randint(0, cfg.vocab_size, 16).tolist()
        pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()
        everything = prompts + [pa, pb]
        ids, lens = L.left_pad_prompts(everything)
        out = np.asarray(L.generate(model, variables, np.asarray(ids),
                                    new, pad_lens=np.asarray(lens),
                                    pad_to=max_len))
        refs = [out[i][int(lens[i]) + len(p):].tolist()
                for i, p in enumerate(everything)]

        eng = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len,
            prefill_chunk=8, block_size=8, prefill_budget=16)
        assert eng.paged and eng.backend.paged
        handles = [eng.submit(p, max_new_tokens=new) for p in prompts]
        eng.run_until_idle()
        assert eng.snapshot()["peak_slots_busy"] == 2
        for p, h, want in zip(prompts, handles, refs):
            assert h.result(1) == want, len(p)
        sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

        # shared 16-token head = 2 full blocks; pa commits, stays
        # RUNNING while pb grafts the SAME physical blocks — one
        # resident copy, the tables prove it
        ha = eng.submit(pa, max_new_tokens=new)
        eng.step()  # 2 of pa's 3 chunks (budget 16)
        eng.step()  # final chunk + finish + first decode token
        assert ha.state == "running"
        hb = eng.submit(pb, max_new_tokens=new)
        eng.step()  # admits + grafts + tail chunk
        be = eng.backend
        sa, sb = ha.slot, hb.slot
        assert (be.tables[sa][:2] == be.tables[sb][:2]).all()
        shared = int(be.tables[sb][0])
        assert be.allocator.is_shared(shared)
        util = be.pool_stats()
        assert util["blocks_shared"] >= 2 and util["shared_frac"] > 0

        # forced divergent write into the shared block: copy-on-write
        # duplicates it bit-identically; the other holder keeps reading
        # the original
        assert be.mgr._cow(sb, 0) is True
        fresh = int(be.tables[sb][0])
        assert fresh != shared
        for leaf in jax.tree_util.tree_leaves(be.cache):
            if getattr(leaf, "ndim", 0) == 4:
                assert np.array_equal(np.asarray(leaf[shared]),
                                      np.asarray(leaf[fresh]))
        eng.run_until_idle()
        # identity survives the graft AND the CoW
        assert ha.result(1) == refs[2]
        assert hb.result(1) == refs[3]
        ps = eng.snapshot()["prefix_cache"]
        assert ps["hits"] >= 1 and ps["reused_tokens"] >= 16
        assert GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step") == sig_decode  # zero re-traces

        # blocking fallback on the SAME paged pool layout: bucketed
        # left-padded whole-prompt refill through the block table
        # (paged_prefill_into_slot) stays token-identical too
        eng_bl = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len,
            block_size=8, stall_free=False, min_bucket=8)
        hb2 = eng_bl.submit(prompts[0], max_new_tokens=new)
        eng_bl.run_until_idle()
        assert hb2.result(1) == refs[0]
        assert eng_bl.backend.allocator.used_count() == 0

    def test_quant_radix_graft_and_cow_exact(self):
        """ISSUE 18 — the radix graft and copy-on-write stay EXACT on a
        quantized pool: an int8 engine WITH sharing emits the identical
        streams as an int8 engine WITHOUT (private blocks only), a CoW
        copy duplicates codes AND the per-block scale rows
        bit-identically, and pool_stats carries the quant observables
        (>= 2x blocks at equal MB is the engine-level acceptance)."""
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        rng = np.random.RandomState(11)
        max_len, new = 64, 6
        head = rng.randint(0, cfg.vocab_size, 16).tolist()
        pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
        pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()

        def make(prefix_mb=None):
            return GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=max_len,
                prefill_chunk=8, block_size=8, prefill_budget=16,
                kv_dtype="int8", prefix_cache_mb=prefix_mb)

        # reference: int8 engine, radix OFF — every block private
        want = []
        for p in (pa, pb):
            e = make(prefix_mb=0)
            h = e.submit(p, max_new_tokens=new)
            e.run_until_idle()
            want.append(h.result(1))

        # radix ON, staggered so pb grafts pa's resident head blocks
        eng = make()
        ha = eng.submit(pa, max_new_tokens=new)
        eng.step()
        eng.step()
        assert ha.state == "running"
        hb = eng.submit(pb, max_new_tokens=new)
        eng.step()
        be = eng.backend
        sa, sb = ha.slot, hb.slot
        assert (be.tables[sa][:2] == be.tables[sb][:2]).all()
        shared = int(be.tables[sb][0])
        assert be.allocator.is_shared(shared)

        # CoW through the quantized pool: codes (4-D) AND scale rows
        # (3-D plane) both copied bit-identically
        assert be.mgr._cow(sb, 0) is True
        fresh = int(be.tables[sb][0])
        assert fresh != shared
        saw_scale = False
        for leaf in jax.tree_util.tree_leaves(be.cache):
            nd = getattr(leaf, "ndim", 0)
            if nd in (3, 4):
                assert np.array_equal(np.asarray(leaf[shared]),
                                      np.asarray(leaf[fresh]))
                saw_scale |= nd == 3
        assert saw_scale, "no kv_scale plane in the quantized pool"
        eng.run_until_idle()
        # EXACTNESS: graft + CoW changed nothing vs the private runs
        assert ha.result(1) == want[0]
        assert hb.result(1) == want[1]

        # quant observables + the equal-MB capacity acceptance
        st = be.pool_stats()
        assert st["kv_dtype"] == "int8"
        assert st["kv_block_bytes"] < st["kv_block_bytes_f32"]
        assert st["kv_scale_bytes_per_block"] > 0
        b_f32 = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len,
            block_size=8, kv_pool_mb=0.5).backend.pool_stats()
        b_q = GenerationEngine.from_model(
            model, variables, num_slots=2, max_len=max_len,
            block_size=8, kv_pool_mb=0.5,
            kv_dtype="int8").backend.pool_stats()
        assert b_q["blocks_total"] >= 2 * b_f32["blocks_total"]
