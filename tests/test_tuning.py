"""Tuning (ParamGridBuilder / CrossValidator / TrainValidationSplit) and
evaluator tests — the param-grid workflow the reference's fitMultiple serves
(SURVEY.md §2.1)."""

import numpy as np
import pytest

import sparkdl_tpu as sdl


def _toy_classification(n=120, seed=0):
    """Linearly separable-ish 2-class data in a features column."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    w = np.array([2.0, -1.0, 0.5, 0.0], np.float32)
    y = (x @ w + 0.3 * rng.randn(n) > 0).astype(np.int64)
    return sdl.DataFrame.fromPydict(
        {"features": [r.tolist() for r in x], "label": y.tolist()},
        numPartitions=2)


def test_param_grid_builder():
    lr = sdl.LogisticRegression()
    grid = (sdl.ParamGridBuilder()
            .addGrid(lr.maxIter, [5, 10])
            .addGrid(lr.stepSize, [0.1, 0.5])
            .build())
    assert len(grid) == 4
    assert {frozenset((p.name, v) for p, v in g.items()) for g in grid} == {
        frozenset([("maxIter", 5), ("stepSize", 0.1)]),
        frozenset([("maxIter", 5), ("stepSize", 0.5)]),
        frozenset([("maxIter", 10), ("stepSize", 0.1)]),
        frozenset([("maxIter", 10), ("stepSize", 0.5)]),
    }
    based = (sdl.ParamGridBuilder()
             .baseOn({lr.maxIter: 7})
             .addGrid(lr.stepSize, [0.1, 0.2]).build())
    assert all(g[lr.maxIter] == 7 for g in based)


def test_random_split():
    df = _toy_classification(100)
    a, b = df.randomSplit([0.7, 0.3], seed=1)
    assert a.count() + b.count() == 100
    assert 60 <= a.count() <= 80
    # deterministic
    a2, _ = df.randomSplit([0.7, 0.3], seed=1)
    assert [r.label for r in a.collect()] == [r.label for r in a2.collect()]
    with pytest.raises(ValueError, match="positive"):
        df.randomSplit([0.5, -0.5])


def test_multiclass_evaluator_metrics():
    df = sdl.DataFrame.fromPydict({
        "label": [0, 0, 1, 1, 2, 2],
        "prediction": [0, 1, 1, 1, 2, 0],
    })
    ev = sdl.MulticlassClassificationEvaluator()
    assert ev.evaluate(df) == pytest.approx(4 / 6)
    f1 = sdl.MulticlassClassificationEvaluator(metricName="f1")
    assert 0.0 < f1.evaluate(df) < 1.0
    with pytest.raises(ValueError, match="Unknown metricName"):
        sdl.MulticlassClassificationEvaluator(metricName="nope").evaluate(df)


def test_regression_evaluator_metrics():
    df = sdl.DataFrame.fromPydict({
        "label": [1.0, 2.0, 3.0], "prediction": [1.0, 2.0, 5.0]})
    assert sdl.RegressionEvaluator(metricName="mae").evaluate(df) == \
        pytest.approx(2 / 3)
    assert sdl.RegressionEvaluator(metricName="rmse").evaluate(df) == \
        pytest.approx(np.sqrt(4 / 3))
    r2 = sdl.RegressionEvaluator(metricName="r2")
    assert r2.isLargerBetter() and r2.evaluate(df) < 1.0
    assert not sdl.RegressionEvaluator(metricName="rmse").isLargerBetter()


def test_binary_evaluator_auc():
    df = sdl.DataFrame.fromPydict({
        "label": [0, 0, 1, 1],
        "probability": [0.1, 0.4, 0.35, 0.8]})
    auc = sdl.BinaryClassificationEvaluator().evaluate(df)
    assert auc == pytest.approx(0.75)
    # perfect separation
    df2 = sdl.DataFrame.fromPydict({
        "label": [0, 0, 1, 1], "probability": [0.1, 0.2, 0.8, 0.9]})
    assert sdl.BinaryClassificationEvaluator().evaluate(df2) == 1.0


def test_cross_validator_selects_reasonable_model():
    df = _toy_classification()
    lr = sdl.LogisticRegression(maxIter=30)
    grid = (sdl.ParamGridBuilder()
            .addGrid(lr.stepSize, [0.001, 0.5]).build())
    cv = sdl.CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=sdl.MulticlassClassificationEvaluator(), numFolds=3)
    model = cv.fit(df)
    assert len(model.avgMetrics) == 2
    # the sane step size must beat the degenerate one, and the refit best
    # model should classify the training data well
    assert model.avgMetrics[1] > model.avgMetrics[0]
    acc = sdl.MulticlassClassificationEvaluator().evaluate(
        model.transform(df))
    assert acc > 0.8


def test_cross_validator_validation():
    lr = sdl.LogisticRegression()
    with pytest.raises(ValueError, match="must be set"):
        sdl.CrossValidator(estimator=lr).fit(_toy_classification(20))
    cv = sdl.CrossValidator(
        estimator=lr, estimatorParamMaps=[{}],
        evaluator=sdl.MulticlassClassificationEvaluator(), numFolds=1)
    with pytest.raises(ValueError, match="numFolds"):
        cv.fit(_toy_classification(20))


def test_train_validation_split():
    df = _toy_classification()
    lr = sdl.LogisticRegression(maxIter=30)
    grid = (sdl.ParamGridBuilder()
            .addGrid(lr.stepSize, [0.001, 0.5]).build())
    tvs = sdl.TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=sdl.MulticlassClassificationEvaluator(),
        trainRatio=0.75)
    model = tvs.fit(df)
    assert len(model.validationMetrics) == 2
    assert model.validationMetrics[1] > model.validationMetrics[0]
    with pytest.raises(ValueError, match="trainRatio"):
        sdl.TrainValidationSplit(
            estimator=lr, estimatorParamMaps=grid,
            evaluator=sdl.MulticlassClassificationEvaluator(),
            trainRatio=1.5).fit(df)


def test_cross_validator_model_persistence(tmp_path):
    df = _toy_classification(60)
    lr = sdl.LogisticRegression(maxIter=20)
    cv = sdl.CrossValidator(
        estimator=lr,
        estimatorParamMaps=sdl.ParamGridBuilder()
            .addGrid(lr.stepSize, [0.3, 0.5]).build(),
        evaluator=sdl.MulticlassClassificationEvaluator(), numFolds=2)
    model = cv.fit(df)
    p = str(tmp_path / "cvm")
    model.save(p)
    loaded = sdl.load(p)
    assert loaded.avgMetrics == model.avgMetrics
    a = [r.prediction for r in model.transform(df).collect()]
    b = [r.prediction for r in loaded.transform(df).collect()]
    assert a == b
