"""Cache-aware flash decode kernel: numerical equivalence vs the dense
cache path (interpret mode — the same kernel the chip compiles), plus
the generation-level token-equality proof once wired into the model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.ops.flash_decode import (decode_fn_for, flash_decode,
                                          supports)
from sparkdl_tpu.utils.platform import is_tpu_backend

# On the real chip the dense reference itself runs through the MXU's
# default f32 precision (bf16 passes), so agreement is ~1e-4 — the same
# platform split as tests/test_ops.py. Interpret mode stays tight.
# bf16 eps is 7.8e-3: a single-pass-MXU-rounded element of value ~2 can
# sit ~7e-3 from the f32 answer (observed on chip: 1 element of 192 at
# max|Δ| 7.3e-3 in the cur=1 one-hot case), and the DENSE reference is
# equally rounded — the comparison tolerance must cover both sides.
ATOL = 1e-2 if is_tpu_backend() else 2e-5
RTOL = 8e-3 if is_tpu_backend() else 2e-5


def dense_cache_attention(q, k_cache, v_cache, cur, pad_lens=None):
    """The in-model dense path's math (models/llama.py grouped einsum),
    restated independently: full-cache scores, slots >= cur and slots
    < pad_lens[b] masked out."""
    b, hq, _, d = q.shape
    _, h_kv, max_len, _ = k_cache.shape
    rep = hq // h_kv
    qg = q.reshape(b, h_kv, rep, 1, d).astype(jnp.float32)
    kf = k_cache.astype(jnp.float32)
    s = jnp.einsum("bgrqd,bgkd->bgrqk", qg, kf) / np.sqrt(d)
    col = jnp.arange(max_len)[None, :]
    valid = col < cur  # [1, max_len]
    if pad_lens is not None:
        valid = valid & (col >= pad_lens[:, None])  # [B, max_len]
        valid = valid[:, None, None, None]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrqk,bgkd->bgrqd", p,
                   v_cache.astype(jnp.float32))
    return o.reshape(b, hq, 1, d)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


@pytest.mark.parametrize("rep", [1, 4])
@pytest.mark.parametrize("cur", [1, 77, 256])
def test_matches_dense_cache_attention(rep, cur):
    b, h_kv, max_len, d = 3, 2, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(cur * 10 + rep), 3)
    q = _rand(ks[0], (b, h_kv * rep, 1, d))
    k = _rand(ks[1], (b, h_kv, max_len, d))
    v = _rand(ks[2], (b, h_kv, max_len, d))
    got = flash_decode(q, k, v, jnp.int32(cur), interpret=True)
    want = dense_cache_attention(q, k, v, cur)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)


def test_left_pad_rows_are_excluded():
    b, h_kv, rep, max_len, d = 4, 2, 2, 384, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = _rand(ks[0], (b, h_kv * rep, 1, d))
    k = _rand(ks[1], (b, h_kv, max_len, d))
    v = _rand(ks[2], (b, h_kv, max_len, d))
    pad = jnp.array([0, 3, 130, 200], jnp.int32)
    cur = jnp.int32(260)
    got = flash_decode(q, k, v, cur, pad, interpret=True)
    want = dense_cache_attention(q, k, v, 260, pad)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)
    # and the mask matters: row with pad=200 differs from its unpadded run
    unpadded = flash_decode(q, k, v, cur, interpret=True)
    assert not np.allclose(got[3], unpadded[3], atol=1e-3)


def test_per_row_cur_matches_per_row_dense():
    """``cur`` as a [B] vector (the continuous-batching slot cache —
    every row at its own fill level) must equal running each row through
    the dense reference with its own scalar cur."""
    b, h_kv, rep, max_len, d = 4, 2, 2, 384, 16
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q = _rand(ks[0], (b, h_kv * rep, 1, d))
    k = _rand(ks[1], (b, h_kv, max_len, d))
    v = _rand(ks[2], (b, h_kv, max_len, d))
    cur = jnp.array([5, 130, 260, 384], jnp.int32)
    pad = jnp.array([0, 3, 10, 100], jnp.int32)
    got = flash_decode(q, k, v, cur, pad, interpret=True)
    want = jnp.concatenate([
        dense_cache_attention(q[r:r + 1], k[r:r + 1], v[r:r + 1],
                              int(cur[r]), pad[r:r + 1])
        for r in range(b)], axis=0)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)
    # rows genuinely differ from a shared-cur run (the mask is per-row)
    shared = flash_decode(q, k, v, jnp.int32(384), pad, interpret=True)
    assert not np.allclose(got[0], shared[0], atol=1e-3)
    with pytest.raises(ValueError, match="scalar or"):
        flash_decode(q, k, v, jnp.zeros((2,), jnp.int32), interpret=True)


def test_bf16_io_f32_accumulation():
    b, h_kv, rep, max_len, d = 2, 2, 4, 128, 32
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(ks[0], (b, h_kv * rep, 1, d)).astype(jnp.bfloat16)
    k = _rand(ks[1], (b, h_kv, max_len, d)).astype(jnp.bfloat16)
    v = _rand(ks[2], (b, h_kv, max_len, d)).astype(jnp.bfloat16)
    got = flash_decode(q, k, v, jnp.int32(100), interpret=True)
    assert got.dtype == jnp.bfloat16
    want = dense_cache_attention(q, k, v, 100)
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               atol=2e-2, rtol=2e-2)


def test_traced_cur_under_jit_one_signature():
    """``cur`` is a traced scalar — one compiled program serves every
    fill level (the generate() while_loop contract)."""
    b, h_kv, rep, max_len, d = 2, 1, 2, 256, 16
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = _rand(ks[0], (b, h_kv * rep, 1, d))
    k = _rand(ks[1], (b, h_kv, max_len, d))
    v = _rand(ks[2], (b, h_kv, max_len, d))
    traces = []

    @jax.jit
    def step(cur):
        traces.append(1)
        return flash_decode(q, k, v, cur, interpret=True)

    for cur in [1, 64, 200, 256]:
        got = step(jnp.int32(cur))
        want = dense_cache_attention(q, k, v, cur)
        np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)
    assert len(traces) == 1


def test_supports_contract():
    assert supports(256)
    assert supports(128)
    assert not supports(100)   # not tiled by 128
    assert not supports(64)    # below one block
    with pytest.raises(ValueError):
        flash_decode(jnp.zeros((1, 1, 1, 8)), jnp.zeros((1, 1, 100, 8)),
                     jnp.zeros((1, 1, 100, 8)), jnp.int32(1),
                     interpret=True)


def test_decode_fn_resolver(monkeypatch):
    from sparkdl_tpu.ops.flash_attention import flash_attention
    assert decode_fn_for(flash_attention) is flash_decode
    assert decode_fn_for(None) is None
    assert decode_fn_for(lambda q, k, v, causal: q) is None
    monkeypatch.setenv("SPARKDL_FLASH_DECODE", "0")
    assert decode_fn_for(flash_attention) is None


class TestGenerateTokenEquality:
    """generate() with the flash attn_fn (which routes per-token decode
    through flash_decode on every platform where the attn_fn is passed
    explicitly) must emit exactly the tokens of the dense run."""

    def test_tokens_equal_greedy(self):
        from sparkdl_tpu.models import llama as L
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg = L.LlamaConfig.tiny()
        model_d = L.LlamaModel(cfg, attn_fn=None)
        model_f = L.LlamaModel(cfg, attn_fn=flash_attention)
        rng = jax.random.PRNGKey(0)
        prompts = [[5, 6, 7], [9, 3, 2, 8, 1]]
        ids, lens = L.left_pad_prompts(prompts)
        variables = model_d.init(rng, jnp.asarray(ids))
        kw = dict(max_new_tokens=8, temperature=0.0, pad_lens=lens,
                  pad_to=128)
        out_d = L.generate(model_d, variables, ids, **kw)
        out_f = L.generate(model_f, variables, ids, **kw)
        np.testing.assert_array_equal(np.asarray(out_d),
                                      np.asarray(out_f))

    def test_default_cache_size_rounds_up_and_stays_token_equal(self):
        """Without pad_to, generate() rounds the cache to the kernel's
        128-slot block multiple when flash decode would engage — the
        default path must actually run the kernel, not silently fall
        back to dense (round-5 review finding), and stay token-equal."""
        from sparkdl_tpu.models import llama as L
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg = L.LlamaConfig.tiny()
        model_d = L.LlamaModel(cfg, attn_fn=None)
        model_f = L.LlamaModel(cfg, attn_fn=flash_attention)
        ids = jnp.asarray([[5, 6, 7], [9, 3, 2]], jnp.int32)
        variables = model_d.init(jax.random.PRNGKey(2), ids)
        kw = dict(max_new_tokens=5, temperature=0.0)  # max_len would be 8
        out_d = L.generate(model_d, variables, ids, **kw)
        out_f = L.generate(model_f, variables, ids, **kw)
        np.testing.assert_array_equal(np.asarray(out_d),
                                      np.asarray(out_f))

    def test_tokens_equal_with_eos_while_loop(self):
        from sparkdl_tpu.models import llama as L
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg = L.LlamaConfig.tiny()
        model_f = L.LlamaModel(cfg, attn_fn=flash_attention)
        model_d = L.LlamaModel(cfg, attn_fn=None)
        rng = jax.random.PRNGKey(1)
        prompts = [[4, 5], [6, 7, 8]]
        ids, lens = L.left_pad_prompts(prompts)
        variables = model_d.init(rng, jnp.asarray(ids))
        kw = dict(max_new_tokens=6, temperature=0.0, pad_lens=lens,
                  pad_to=128, eos_id=2, return_steps=True)
        out_d, steps_d = L.generate(model_d, variables, ids, **kw)
        out_f, steps_f = L.generate(model_f, variables, ids, **kw)
        np.testing.assert_array_equal(np.asarray(out_d),
                                      np.asarray(out_f))
        assert int(steps_d) == int(steps_f)
