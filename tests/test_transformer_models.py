"""BERT / Llama family tests: shapes, causality, LoRA masking, TP sharding
equivalence, and ring-attention integration — all on the 8-device CPU mesh."""

import functools

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from sparkdl_tpu.core import runtime
from sparkdl_tpu.models.bert import (BertConfig, BertEncoder,
                                     BertForSequenceClassification,
                                     glue_loss_fn)
from sparkdl_tpu.models.llama import (LlamaConfig, LlamaModel,
                                      causal_lm_loss_fn, lora_mask,
                                      lora_optimizer)
from sparkdl_tpu.parallel import (lora_rules, ring_attention, shard_params,
                                  transformer_tp_rules)
from sparkdl_tpu.runner import TrainState, XlaRunner


def _bert_batch(cfg, B=8, S=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": rng.randint(0, cfg.vocab_size, size=(B, S)),
        "attention_mask": np.ones((B, S), np.int32),
        "label": rng.randint(0, 2, size=(B,)),
    }


class TestBert:
    def test_forward_shapes(self):
        cfg = BertConfig.tiny()
        model = BertEncoder(cfg)
        ids = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), ids)
        seq, pooled = model.apply(variables, ids)
        assert seq.shape == (2, 16, cfg.hidden_size)
        assert pooled.shape == (2, cfg.hidden_size)

    def test_attention_mask_blocks_padding(self):
        """Changing tokens under a zeroed mask must not change outputs."""
        cfg = BertConfig.tiny()
        model = BertEncoder(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, size=(1, 16))
        mask = np.ones((1, 16), np.int32)
        mask[:, 8:] = 0
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))
        _, p1 = model.apply(variables, jnp.asarray(ids), jnp.asarray(mask))
        ids2 = ids.copy()
        ids2[:, 8:] = (ids2[:, 8:] + 7) % cfg.vocab_size
        _, p2 = model.apply(variables, jnp.asarray(ids2), jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=1e-5, atol=1e-6)

    def test_glue_finetune_learns(self):
        """Config-4 shape: BERT classification fine-tune through the runner
        on the 8-device mesh; loss must drop."""
        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg, num_classes=2)
        batch0 = _bert_batch(cfg, B=16)
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.asarray(batch0["input_ids"])))

        def apply_fn(params, batch):
            return model.apply(params, batch["input_ids"],
                               batch["attention_mask"])

        def data():
            while True:
                yield _bert_batch(cfg, B=16, seed=1)

        res = XlaRunner(np=8).run(lambda ctx: ctx.fit(
            loss_fn=glue_loss_fn(), params=variables,
            tx=optax.adam(1e-3), apply_fn=apply_fn, data=data(),
            num_steps=10, log_every=3))
        losses = [h["loss"] for h in res["history"]]
        assert losses[-1] < losses[0]


class TestLlama:
    def test_forward_and_causality(self):
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, size=(2, 16))
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))
        logits = model.apply(variables, jnp.asarray(ids))
        assert logits.shape == (2, 16, cfg.vocab_size)
        # causality: mutate the last token — logits at positions < 15 fixed
        ids2 = ids.copy()
        ids2[:, -1] = (ids2[:, -1] + 3) % cfg.vocab_size
        logits2 = model.apply(variables, jnp.asarray(ids2))
        np.testing.assert_allclose(np.asarray(logits[:, :-1]),
                                   np.asarray(logits2[:, :-1]),
                                   rtol=1e-5, atol=1e-6)

    def test_lora_mask_and_freeze(self):
        cfg = LlamaConfig.tiny(lora_rank=4)
        model = LlamaModel(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), ids)
        mask = lora_mask(variables)
        leaves = jax.tree_util.tree_leaves_with_path(variables)
        n_lora = sum(bool(m) for m in jax.tree_util.tree_leaves(mask))
        # 2 layers × (q_proj + v_proj) × (A + B) = 8 adapter leaves
        assert n_lora == 8

        # one optimizer step: base weights must be bit-identical after
        state = TrainState.create(None, variables, lora_optimizer(1e-2))
        grads = jax.tree_util.tree_map(jnp.ones_like, variables)
        new = state.apply_gradients(grads)

        from sparkdl_tpu.parallel.sharding import path_str
        for (path, old), new_leaf in zip(
                jax.tree_util.tree_leaves_with_path(variables),
                jax.tree_util.tree_leaves(new.params)):
            s = path_str(path)
            if "lora" in s:
                assert not np.allclose(np.asarray(old), np.asarray(new_leaf))
            else:
                np.testing.assert_array_equal(np.asarray(old),
                                              np.asarray(new_leaf))

    def test_lora_zero_init_is_identity(self):
        """rank>0 with zero-init B must match the rank=0 model exactly
        (same seed ⇒ same base weights)."""
        ids = jnp.zeros((1, 8), jnp.int32)
        m0 = LlamaModel(LlamaConfig.tiny())
        m1 = LlamaModel(LlamaConfig.tiny(lora_rank=4))
        v1 = m1.init(jax.random.PRNGKey(0), ids)
        out1 = m1.apply(v1, ids)
        # strip adapters, rename base params into the rank-0 structure
        out0 = m0.apply(m0.init(jax.random.PRNGKey(0), ids), ids)
        # flax init RNG folding differs once adapters exist, so compare
        # through the B=0 algebra instead: adapters contribute (alpha/r)·xAB
        # with B=0 ⇒ exact equality against the same v1 base weights.
        from flax.traverse_util import flatten_dict, unflatten_dict
        flat = {k: v for k, v in flatten_dict(v1, sep="/").items()
                if "lora" not in k}
        v0 = unflatten_dict({tuple(k.split("/")): v for k, v in flat.items()})
        out_base = m0.apply(v0, ids)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out_base),
                                   rtol=1e-5, atol=1e-6)

    def test_tp_sharding_equivalence(self):
        """Llama forward with params sharded by transformer_tp_rules over a
        2-D (data×model) mesh must equal the replicated forward."""
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        rng = np.random.RandomState(1)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(4, 16)))
        variables = model.init(jax.random.PRNGKey(0), ids)
        expected = model.apply(variables, ids)

        mesh = runtime.make_mesh({"data": 4, "model": 2})
        placed = shard_params(jax.tree_util.tree_map(np.asarray, variables),
                              mesh, transformer_tp_rules())
        # sanity: q_proj kernel is actually split over the model axis
        q = placed["params"]["layer_0"]["attn"]["q_proj"]["base"]["kernel"]
        assert {s.data.shape for s in q.addressable_shards} == {(128, 64)}

        out = jax.jit(model.apply)(placed, ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_train_step_param_rules_pins_tp_layout(self):
        """make_train_step(param_rules=...) must emit params sharded per the
        rules, even when inputs arrive replicated."""
        from sparkdl_tpu.runner import TrainState, make_train_step
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        rng = np.random.RandomState(5)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(4, 16)))
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), ids))
        mesh = runtime.make_mesh({"data": 4, "model": 2})
        state = TrainState.create(model.apply, variables, optax.sgd(1e-2))
        step = make_train_step(causal_lm_loss_fn(), mesh, data_axis="data",
                               param_rules=transformer_tp_rules())
        with mesh:
            new_state, m = step(state, {"input_ids": ids})
        q = new_state.params["params"]["layer_0"]["attn"]["q_proj"]["base"][
            "kernel"]
        # output (hidden=128) dim split over model axis (2) → (128, 64)
        assert {s.data.shape for s in q.addressable_shards} == {(128, 64)}
        assert np.isfinite(float(m["loss"]))

    def test_lora_tp_rules_on_real_params(self):
        cfg = LlamaConfig.tiny(lora_rank=4)
        model = LlamaModel(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), ids)
        mesh = runtime.make_mesh({"data": 4, "model": 2})
        placed = shard_params(jax.tree_util.tree_map(np.asarray, variables),
                              mesh, lora_rules(transformer_tp_rules()))
        b = placed["params"]["layer_0"]["attn"]["q_proj"]["lora_b"]["kernel"]
        # B: (r, out) inherits output sharding → (4, 64) shards
        assert {s.data.shape for s in b.addressable_shards} == {(4, 64)}

    def test_ring_attention_integration(self):
        """LlamaModel with sequence-parallel ring attention must match the
        dense-attention model."""
        cfg = LlamaConfig.tiny()
        mesh = runtime.make_mesh({"sp": 8})
        dense_model = LlamaModel(cfg)
        ring_model = LlamaModel(cfg, attn_fn=functools.partial(
            ring_attention, mesh=mesh, axis="sp"))
        rng = np.random.RandomState(2)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(2, 64)))
        variables = dense_model.init(jax.random.PRNGKey(0), ids)
        expected = dense_model.apply(variables, ids)
        got = jax.jit(ring_model.apply)(variables, ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_lm_loss_trains(self):
        cfg = LlamaConfig.tiny(lora_rank=4)
        model = LlamaModel(cfg)
        rng = np.random.RandomState(3)
        ids = rng.randint(0, cfg.vocab_size, size=(16, 16))
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.asarray(ids)))

        def data():
            while True:
                yield {"input_ids": ids}

        res = XlaRunner(np=8).run(lambda ctx: ctx.fit(
            loss_fn=causal_lm_loss_fn(), params=variables,
            tx=lora_optimizer(5e-3),
            apply_fn=model.apply,
            data=data(), num_steps=8, log_every=2))
        losses = [h["loss"] for h in res["history"]]
        assert losses[-1] < losses[0]


class TestLlamaGeneration:
    """KV-cache decode (models/llama.py generate) and the generation UDF."""

    def _setup(self):
        from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
        cfg = LlamaConfig.tiny()
        model = LlamaModel(cfg)
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 8)), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), ids)
        return cfg, model, variables, ids

    def test_kv_cache_matches_full_reforward(self):
        from sparkdl_tpu.models.llama import generate
        cfg, model, variables, ids = self._setup()
        cur = ids
        for _ in range(5):
            logits = model.apply(variables, cur)
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             -1).astype(jnp.int32)
            cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
        out = generate(model, variables, ids, 5)
        assert (np.asarray(out) == np.asarray(cur)).all()

    def test_pad_to_and_errors(self):
        from sparkdl_tpu.models.llama import generate
        cfg, model, variables, ids = self._setup()
        out = generate(model, variables, ids, 3, pad_to=32)
        assert out.shape == (2, 11)
        with pytest.raises(ValueError, match="pad_to"):
            generate(model, variables, ids, 5, pad_to=10)

    def test_left_padded_generate_matches_unpadded(self):
        """One masked left-padded prefill must emit the same greedy tokens
        as per-row unpadded generation (round-2 verdict weak #4)."""
        from sparkdl_tpu.models.llama import generate, left_pad_prompts
        cfg, model, variables, _ = self._setup()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (6, 3, 8)]
        ids, pads = left_pad_prompts(prompts)
        assert ids.shape == (3, 8) and pads.tolist() == [2, 5, 0]
        batch = np.asarray(generate(model, variables, ids, 4,
                                    pad_lens=pads))
        for r, p in enumerate(prompts):
            solo = np.asarray(generate(
                model, variables, np.asarray([p], np.int32), 4))
            np.testing.assert_array_equal(batch[r, pads[r]:], solo[0])

    def test_generation_udf_left_pads_two_programs(self):
        """A mixed-length column runs as exactly TWO compiled programs
        (one masked prefill + one scan decode), with no duplicate-row fill
        (round-2 verdict weak #4 / ADVICE r1 item 3)."""
        import pandas as pd

        import sparkdl_tpu as sdl
        from sparkdl_tpu.models import llama as llama_mod
        from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

        cfg, model, variables, _ = self._setup()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (5, 8, 5, 3)]
        df = sdl.DataFrame.fromPandas(pd.DataFrame({"prompt": prompts}))
        registerGenerationUDF("gen", model, variables, max_new_tokens=4)
        try:
            pre0 = llama_mod._prefill._cache_size()
            dec0 = llama_mod._decode._cache_size()
            out = sdl.applyUDF(df, "gen", "prompt", "completion").toPandas()
            assert llama_mod._prefill._cache_size() - pre0 <= 1
            assert llama_mod._decode._cache_size() - dec0 <= 1

            # a column with a DIFFERENT length mix (same max) reuses both
            prompts2 = [rng.randint(0, cfg.vocab_size, n).tolist()
                        for n in (8, 1, 2, 7)]
            df2 = sdl.DataFrame.fromPandas(pd.DataFrame({"prompt": prompts2}))
            pre1 = llama_mod._prefill._cache_size()
            dec1 = llama_mod._decode._cache_size()
            out2 = sdl.applyUDF(df2, "gen", "prompt", "c2").toPandas()
            assert llama_mod._prefill._cache_size() == pre1
            assert llama_mod._decode._cache_size() == dec1
        finally:
            unregisterUDF("gen")
        for p, c in zip(prompts, out["completion"]):
            assert len(c) == len(p) + 4
            assert list(c[:len(p)]) == p
        for p, c in zip(prompts2, out2["c2"]):
            assert len(c) == len(p) + 4
            assert list(c[:len(p)]) == p


class TestBertFlashAndDataFrame:
    def test_bert_flash_matches_dense_with_padding(self):
        """Explicit flash attn_fn (interpret mode on CPU) must reproduce the
        dense path through the full encoder, padding mask included."""
        from sparkdl_tpu.ops import flash_attention
        cfg = BertConfig.tiny()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, size=(2, 32))
        mask = np.ones((2, 32), np.int32)
        mask[1, 20:] = 0
        dense_model = BertEncoder(cfg, attn_fn=None)
        variables = dense_model.init(jax.random.PRNGKey(0),
                                     jnp.asarray(ids))
        _, pd_ = dense_model.apply(variables, jnp.asarray(ids),
                                   jnp.asarray(mask))
        flash_model = BertEncoder(cfg, attn_fn=functools.partial(
            flash_attention, block_q=16, block_k=16))
        _, pf = flash_model.apply(variables, jnp.asarray(ids),
                                  jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(pf), np.asarray(pd_),
                                   rtol=2e-4, atol=2e-4)

    def test_config4_dataframe_to_finetune_end_to_end(self):
        """BASELINE config 4 'with Spark DataFrame reader': a tokenized
        GLUE-shaped DataFrame (int-list columns) streams through iterBatches
        into ctx.fit(bert_finetune_loss, with_rng=True); eval accuracy on
        held-out rows beats chance (round-2 verdict missing #5)."""
        import sparkdl_tpu as sdl
        from sparkdl_tpu.models.bert import bert_finetune_loss

        cfg = BertConfig.tiny()
        S, n = 12, 96
        rng = np.random.RandomState(0)
        # learnable synthetic "GLUE": the first token comes from a small
        # reused id set (so train and test share embeddings and the rule
        # GENERALIZES — a wide-vocab rule would just be memorized);
        # label = first token in the upper half of that set
        seqs, masks, labels = [], [], []
        for i in range(n):
            ln = rng.randint(6, S + 1)
            toks = rng.randint(1, cfg.vocab_size, size=(ln,))
            toks[0] = 2 + rng.randint(0, 10)
            seqs.append(toks.tolist() + [0] * (S - ln))
            masks.append([1] * ln + [0] * (S - ln))
            labels.append(int(toks[0] >= 7))
        df = sdl.DataFrame.fromPydict(
            {"input_ids": seqs, "attention_mask": masks, "label": labels},
            numPartitions=4)
        train_df, test_df = df.randomSplit([0.75, 0.25], seed=1)

        model = BertForSequenceClassification(cfg, num_classes=2)
        variables = jax.tree_util.tree_map(np.asarray, model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, S), jnp.int32)))

        B = 16

        def batches(d, epochs):
            for _ in range(epochs):
                for rb in d.iterBatches(B):
                    if rb.num_rows < B:
                        continue  # static shapes: drop the partial tail
                    yield {
                        "input_ids": np.asarray(
                            rb.column("input_ids").to_pylist(), np.int32),
                        "attention_mask": np.asarray(
                            rb.column("attention_mask").to_pylist(),
                            np.int32),
                        "label": np.asarray(
                            rb.column("label").to_pylist(), np.int32),
                    }

        # np=2 (not 8): this box exposes 1 physical core; an 8-thread
        # collective rendezvous over ~100 steps starves past XLA's 40s
        # watchdog. DP-8 training is covered by test_glue_finetune_learns.
        steps = sum(1 for _ in batches(train_df, 30))
        res = XlaRunner(np=2).run(lambda ctx: ctx.fit(
            loss_fn=bert_finetune_loss(model), params=variables,
            tx=optax.adam(2e-3), data=batches(train_df, 30),
            num_steps=steps, with_rng=True, log_every=steps))
        trained = jax.tree_util.tree_map(np.asarray, res["state"].params)

        test_rows = test_df.collect()
        ids = np.asarray([r["input_ids"] for r in test_rows], np.int32)
        msk = np.asarray([r["attention_mask"] for r in test_rows], np.int32)
        y = np.asarray([r["label"] for r in test_rows])
        logits = np.asarray(model.apply(trained, ids, msk))
        acc = float((logits.argmax(-1) == y).mean())
        assert acc >= 0.75, f"accuracy {acc} not above chance"


def test_bert_maskless_attn_fn_contract():
    """A plain (q,k,v,causal=...) attn_fn (the ring/Ulysses signature —
    ring_attention.dense_attention itself grew kv_mask support in r5, so a
    bare lambda stands in) works when no attention_mask is given; with a
    padding mask it raises a clear error instead of silently ignoring the
    padding (code-review r3)."""
    from sparkdl_tpu.parallel.ring_attention import dense_attention

    def maskless_attn(q, k, v, causal=False):
        return dense_attention(q, k, v, causal)

    cfg = BertConfig.tiny()
    ids = np.random.RandomState(2).randint(0, cfg.vocab_size,
                                           (2, 16)).astype(np.int32)
    m = BertEncoder(cfg, attn_fn=maskless_attn)
    v = m.init(jax.random.PRNGKey(0), ids)
    _, pooled = m.apply(v, ids)  # no mask: fine
    ref = BertEncoder(cfg, attn_fn=None)
    _, pooled_ref = ref.apply(v, ids)
    np.testing.assert_allclose(np.asarray(pooled), np.asarray(pooled_ref),
                               rtol=2e-4, atol=2e-4)
    with pytest.raises(TypeError, match="kv_mask"):
        m.apply(v, ids, np.ones((2, 16), np.int32))


def test_sampling_top_k_top_p():
    """top-k restricts sampling to the k best logits; top-p to the nucleus.
    Distribution-level check on the _sample primitive (compiled shapes are
    static; filtering is rank-based)."""
    from sparkdl_tpu.models.llama import _sample
    logits = jnp.asarray(np.log(np.array(
        [[0.5, 0.3, 0.15, 0.04, 0.01]], np.float32)))
    keys = jax.random.split(jax.random.PRNGKey(0), 300)
    # top_k=2: only ids {0,1} can appear
    toks = np.asarray([_sample(logits, k, 1.0, 2, 1.0)[0] for k in keys[:50]])
    assert set(toks.tolist()) <= {0, 1}
    # top_p=0.75: nucleus {0,1} (0.5 < 0.75, 0.5+0.3 >= 0.75; off the
    # exact cumulative boundaries so f32 rounding can't flip membership)
    toks = np.asarray([_sample(logits, k, 1.0, 0, 0.75)[0]
                       for k in keys[:50]])
    assert set(toks.tolist()) <= {0, 1}
    # top_p=0.9: {0,1,2} (0.8 < 0.9 <= 0.95)
    toks = np.asarray([_sample(logits, k, 1.0, 0, 0.9)[0]
                       for k in keys])
    assert set(toks.tolist()) <= {0, 1, 2} and 2 in set(toks.tolist())
    # greedy ignores both
    assert int(_sample(logits, keys[0], 0.0, 2, 0.5)[0]) == 0


def test_generate_with_sampling_args():
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    out = generate(model, v, ids, 4, temperature=0.8, top_k=10, top_p=0.9,
                   rng=jax.random.PRNGKey(1))
    assert out.shape == (2, 10)
    assert (np.asarray(out[:, :6]) == ids).all()


def test_sampling_validation():
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    from sparkdl_tpu.udf import registerGenerationUDF
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))
    with pytest.raises(ValueError, match="top_p"):
        generate(model, v, np.ones((1, 3), np.int32), 2, temperature=0.5,
                 top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, v, np.ones((1, 3), np.int32), 2, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        registerGenerationUDF("bad", model, v, top_p=0.0)
    with pytest.raises(TypeError, match="eos_id"):
        registerGenerationUDF("bad", model, v, eos_id="</s>")


def test_generation_udf_streams_without_full_materialization(monkeypatch):
    """The generation UDF walks the column via iterBatches — O(batchRows)
    host rows, never a whole-column toPandas (round-3 verdict Next #5).
    Many-partition mixed-length column: streamed outputs must equal per-row
    solo generation, every generate() call must see <= batchRows rows, and
    DataFrame.toPandas must never run on the input."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.core.frame import DataFrame as DF
    from sparkdl_tpu.models import llama as llama_mod
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 2, 7, 3, 4, 6, 1, 2, 5, 3)]
    df = sdl.DataFrame.fromPydict({"p": prompts}, numPartitions=4)

    batch_rows_seen = []
    real_generate = llama_mod.generate

    def spy_generate(model_, vars_, ids, *a, **kw):
        batch_rows_seen.append(len(ids))
        return real_generate(model_, vars_, ids, *a, **kw)

    monkeypatch.setattr(llama_mod, "generate", spy_generate)
    monkeypatch.setattr(
        DF, "toPandas",
        lambda self: (_ for _ in ()).throw(
            AssertionError("generation UDF materialized the column")))

    registerGenerationUDF("sg", model, v, max_new_tokens=3, batchRows=4)
    try:
        out = sdl.applyUDF(df, "sg", "p", "c")
        rows = out.collect()
    finally:
        unregisterUDF("sg")

    assert len(batch_rows_seen) == 3  # ceil(10/4) chunks
    assert all(n <= 4 for n in batch_rows_seen)
    assert len(rows) == 10
    assert out.numPartitions == df.numPartitions  # contract preserved
    for p, r in zip(prompts, rows):
        solo = np.asarray(real_generate(
            model, v, np.asarray([p], np.int32), 3))
        assert list(r["c"]) == solo[0].tolist()


def test_generation_udf_single_compiled_signature():
    """Every chunk of a multi-chunk column — including the short tail —
    runs on ONE compiled (batchRows, max_len) prefill + decode signature
    (round-4 verdict Next #9: the tail fills with duplicate rows to
    batchRows, so a 70-rows/batchRows-64-shaped column compiles exactly
    one program pair, not a second tail-sized one)."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.models import llama as llama_mod
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
    from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))
    rng = np.random.RandomState(8)
    # 18 rows / batchRows=8 → chunks of 8, 8, 2(+6 fill) — same shape
    # class as the verdict's 70/64 example, at test-sized cost
    prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
               for n in ([3, 5, 2, 4, 6, 3, 2, 5] * 2 + [4, 3])]
    df = sdl.DataFrame.fromPydict({"p": prompts})

    llama_mod._prefill.clear_cache()
    llama_mod._decode.clear_cache()
    registerGenerationUDF("sig", model, v, max_new_tokens=2, batchRows=8)
    try:
        rows = sdl.applyUDF(df, "sig", "p", "c").collect()
    finally:
        unregisterUDF("sig")
    assert len(rows) == 18
    assert llama_mod._prefill._cache_size() == 1
    assert llama_mod._decode._cache_size() == 1


def test_sequence_classification_udf():
    """The config-4 serving half: ragged token-id columns stream through
    ONE compiled encoder-classifier program (right-pad + attention mask),
    predictions equal per-row solo classification."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.models.bert import (BertConfig,
                                         BertForSequenceClassification)
    from sparkdl_tpu.udf import (registerSequenceClassificationUDF,
                                 unregisterUDF)

    cfg = BertConfig.tiny()
    model = BertForSequenceClassification(cfg, num_classes=3)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    rng = np.random.RandomState(0)
    rows = [rng.randint(0, cfg.vocab_size, n).tolist()
            for n in (8, 3, 12, 5, 7)]
    df = sdl.DataFrame.fromPydict({"tokens": rows}, numPartitions=2)

    registerSequenceClassificationUDF("cls", model, v, batchRows=3)
    try:
        out = sdl.applyUDF(df, "cls", "tokens", "label")
        got = [r["label"] for r in out.collect()]
        assert out.numPartitions == df.numPartitions
    finally:
        unregisterUDF("cls")

    for toks, lab in zip(rows, got):
        ids = np.asarray([toks], np.int32)
        mask = np.ones_like(ids)
        logits = model.apply(v, jnp.asarray(ids), jnp.asarray(mask))
        assert int(np.asarray(logits).argmax(-1)[0]) == lab

    # empty and null prompts rejected with the GLOBAL row named
    bad = sdl.DataFrame.fromPydict({"tokens": [[1, 2], []]})
    nul = sdl.DataFrame.fromPydict(
        {"tokens": [[1], [2], [3], None]}, numPartitions=2)
    registerSequenceClassificationUDF("cls2", model, v, batchRows=2)
    try:
        with pytest.raises(ValueError, match="row 1 is an empty"):
            sdl.applyUDF(bad, "cls2", "tokens", "label")
        with pytest.raises(ValueError, match="row 3 is null"):
            sdl.applyUDF(nul, "cls2", "tokens", "label")
    finally:
        unregisterUDF("cls2")


def test_text_generation_udf_string_columns():
    """registerTextGenerationUDF: string prompts → encode → the streamed
    token UDF → decode, with the prompt stripped from the completion and
    helper columns dropped."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    from sparkdl_tpu.udf import registerTextGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))

    # toy char-level codec over a..z (ids 1..26, vocab 512 >> 27)
    encode = lambda s: [ord(c) - ord("a") + 1 for c in s]
    decode = lambda ids: "".join(chr(i - 1 + ord("a")) for i in ids)

    texts = ["hello", "ab", "generate"]
    df = sdl.DataFrame.fromPydict({"text": texts}, numPartitions=2)
    registerTextGenerationUDF("complete", model, v, encode, decode,
                              max_new_tokens=4, batchRows=2)
    try:
        out = sdl.applyUDF(df, "complete", "text", "rest").toPandas()
    finally:
        unregisterUDF("complete")
    assert list(out.columns) == ["text", "rest"]
    for t, rest in zip(texts, out["rest"]):
        solo = np.asarray(generate(
            model, v, np.asarray([encode(t)], np.int32), 4))[0]
        assert rest == decode([int(x) for x in solo[len(encode(t)):]])

    with pytest.raises(TypeError, match="encode and decode"):
        registerTextGenerationUDF("bad", model, v, "not-callable", decode)

    # an empty prompt error must name the USER's column, not the hidden
    # internal ids column
    df_bad = sdl.DataFrame.fromPydict({"text": ["ok", ""]})
    registerTextGenerationUDF("t2", model, v, encode, decode,
                              max_new_tokens=2)
    try:
        with pytest.raises(ValueError, match="'text' row 1"):
            sdl.applyUDF(df_bad, "t2", "text", "out")
    finally:
        unregisterUDF("t2")


def test_generation_eos_stops_rows():
    """Rows that emit eos keep emitting it (static shapes); the UDF trims
    the tail to one eos."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    import sparkdl_tpu as sdl
    from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = np.asarray([[1, 2, 3]], np.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    # find what greedy emits first, then use THAT id as eos: the row is
    # done immediately and every subsequent token must equal eos
    free = np.asarray(generate(model, v, ids, 5))
    eos = int(free[0, 3])
    out = np.asarray(generate(model, v, ids, 5, eos_id=eos))
    assert (out[0, 3:] == eos).all()

    df = sdl.DataFrame.fromPydict({"p": [[1, 2, 3]]})
    registerGenerationUDF("eos_g", model, v, max_new_tokens=5, eos_id=eos)
    try:
        res = sdl.applyUDF(df, "eos_g", "p", "c").toPandas()
    finally:
        unregisterUDF("eos_g")
    c = list(res["c"][0])
    assert c == [1, 2, 3, eos]  # trimmed to one eos after the prompt


class TestFlashPrefill:
    """Generation prefill through the flash kernel (interpret mode on CPU)
    must reproduce the dense cache-attention path — long prompts then never
    materialize O(S·max_len) scores on TPU, where flash is the default."""

    def _setup(self):
        from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
        cfg = LlamaConfig.tiny()
        dense_model = LlamaModel(cfg)
        v = dense_model.init(jax.random.PRNGKey(0),
                             np.zeros((1, 4), np.int32))
        return cfg, dense_model, v

    def test_unpadded_prefill_equivalence(self):
        from sparkdl_tpu.models.llama import LlamaModel, generate
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg, dense_model, v = self._setup()
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 24)).astype(np.int32)
        ref = np.asarray(generate(dense_model, v, ids, 6))
        flash_model = LlamaModel(cfg, attn_fn=flash_attention)
        got = np.asarray(generate(flash_model, v, ids, 6))
        np.testing.assert_array_equal(got, ref)

    def test_left_padded_prefill_equivalence(self):
        from sparkdl_tpu.models.llama import (LlamaModel, generate,
                                              left_pad_prompts)
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg, dense_model, v = self._setup()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (9, 4, 16, 1)]
        ids, pads = left_pad_prompts(prompts)
        ref = np.asarray(generate(dense_model, v, ids, 5, pad_lens=pads))
        flash_model = LlamaModel(cfg, attn_fn=flash_attention)
        got = np.asarray(generate(flash_model, v, ids, 5, pad_lens=pads))
        for r, p in enumerate(prompts):
            np.testing.assert_array_equal(got[r, pads[r]:], ref[r, pads[r]:])

    def test_maskless_attn_fn_falls_back_with_padding(self):
        """An explicit attn_fn without kv_mask support (ring/Ulysses
        shapes) must NOT be used for a left-padded prefill — the dense
        path runs instead and results stay correct."""
        from sparkdl_tpu.models.llama import (LlamaModel, generate,
                                              left_pad_prompts)
        from sparkdl_tpu.parallel.ring_attention import dense_attention

        cfg, dense_model, v = self._setup()

        def maskless(q, k, v_, causal=False):
            return dense_attention(q, k, v_, causal)

        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (7, 3)]
        ids, pads = left_pad_prompts(prompts)
        ref = np.asarray(generate(dense_model, v, ids, 4, pad_lens=pads))
        m = LlamaModel(cfg, attn_fn=maskless)
        got = np.asarray(generate(m, v, ids, 4, pad_lens=pads))
        np.testing.assert_array_equal(got, ref)

    def test_var_kwargs_attn_fn_rejected_with_padding(self):
        """A **kwargs pass-through wrapper would swallow kv_mask and attend
        to pad tokens — only an explicit kv_mask parameter proves support,
        so the wrapper must not be called for a left-padded prefill."""
        from sparkdl_tpu.models.llama import (LlamaModel, generate,
                                              left_pad_prompts)
        from sparkdl_tpu.parallel.ring_attention import dense_attention

        cfg, dense_model, v = self._setup()
        calls = []

        def swallower(q, k, v_, causal=False, **kw):
            calls.append(kw)
            return dense_attention(q, k, v_, causal)

        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, cfg.vocab_size, n).tolist()
                   for n in (8, 3)]
        ids, pads = left_pad_prompts(prompts)
        ref = np.asarray(generate(dense_model, v, ids, 4, pad_lens=pads))
        got = np.asarray(generate(LlamaModel(cfg, attn_fn=swallower), v,
                                  ids, 4, pad_lens=pads))
        np.testing.assert_array_equal(got, ref)
        assert not calls  # fell back to dense; the wrapper never ran

    def test_chunked_prefill_first_chunk_flag(self):
        """A chunked multi-call prefill: chunk 2 (cache index > 0) must
        attend the earlier cache — logits equal the single-call prefill of
        the full prompt. first_chunk defaults to False, so an unaware
        chunked caller is correct by default; only cache-index-0 callers
        opt into the square flash fast path explicitly."""
        import jax.numpy as jnp
        from sparkdl_tpu.models.llama import (LlamaModel, generate,
                                              init_cache)
        from sparkdl_tpu.ops.flash_attention import flash_attention

        cfg, dense_model, v = self._setup()
        ids = np.random.RandomState(5).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)

        def chunked_last_logits(model):
            cache = init_cache(model, 2, 16)
            variables = {"params": v["params"], "cache": cache}
            out1, mut = model.apply(variables, jnp.asarray(ids[:, :8]),
                                    decode=True, first_chunk=True,
                                    mutable=["cache"])
            variables = {"params": v["params"], "cache": mut["cache"]}
            out2, _ = model.apply(variables, jnp.asarray(ids[:, 8:]),
                                  decode=True, first_chunk=False,
                                  mutable=["cache"])
            return np.asarray(out2[:, -1].astype(jnp.float32))

        def single_last_logits(model):
            cache = init_cache(model, 2, 16)
            out, _ = model.apply({"params": v["params"], "cache": cache},
                                 jnp.asarray(ids), decode=True,
                                 mutable=["cache"])
            return np.asarray(out[:, -1].astype(jnp.float32))

        ref = single_last_logits(dense_model)
        np.testing.assert_allclose(chunked_last_logits(dense_model), ref,
                                   atol=1e-5)
        # with flash configured, chunk 2 must take the dense path (the
        # square kernel can't see earlier cache) and still match
        flash_model = LlamaModel(cfg, attn_fn=flash_attention)
        np.testing.assert_allclose(chunked_last_logits(flash_model), ref,
                                   atol=1e-4)

    def test_sequence_parallel_prefill_via_ring_attention(self):
        """Sequence-parallel SERVING prefill: generate() with
        attn_fn=ring_attention runs the prompt's attention sharded over the
        sp mesh axis (KV hops over ICI) — the S^2 prefill compute scales
        across chips while the KV cache and decode stay as today. Tokens
        must equal the single-device dense run."""
        import functools

        from sparkdl_tpu.core import runtime
        from sparkdl_tpu.models.llama import LlamaModel, generate
        from sparkdl_tpu.parallel.ring_attention import ring_attention

        cfg, dense_model, v = self._setup()
        mesh = runtime.make_mesh({"sp": 8})
        sp_model = LlamaModel(cfg, attn_fn=functools.partial(
            ring_attention, mesh=mesh, axis="sp"))
        # 32 = 8 shards x 4 tokens each
        ids = np.random.RandomState(6).randint(
            0, cfg.vocab_size, (2, 32)).astype(np.int32)
        ref = np.asarray(generate(dense_model, v, ids, 5))
        got = np.asarray(generate(sp_model, v, ids, 5))
        np.testing.assert_array_equal(got, ref)

    def test_tensor_parallel_serving(self):
        """TP-sharded generation: Megatron-pattern param shards over the
        model axis (how a too-big-for-one-chip Llama serves on a slice).
        The jitted prefill/decode honor the input shardings — XLA inserts
        the collectives; tokens must equal the unsharded run, including
        the EOS while_loop path."""
        from sparkdl_tpu.core import runtime
        from sparkdl_tpu.models.llama import LlamaModel, generate
        from sparkdl_tpu.parallel import shard_params, transformer_tp_rules

        cfg, dense_model, v = self._setup()
        ids = np.random.RandomState(10).randint(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)
        ref = np.asarray(generate(dense_model, v, ids, 6))

        mesh = runtime.make_mesh({"data": 2, "model": 4})
        placed = shard_params(v, mesh, transformer_tp_rules())
        got = np.asarray(generate(dense_model, placed, ids, 6))
        np.testing.assert_array_equal(got, ref)

        eos = int(ref[0, 12])
        out, n_steps = generate(dense_model, placed,
                                np.repeat(ids[:1], 2, 0), 6,
                                eos_id=eos, return_steps=True)
        assert n_steps < 6
        assert (np.asarray(out)[:, 12:] == eos).all()

    def test_sequence_parallel_prefill_via_ulysses(self):
        """Ulysses all-to-all prefill: heads scatter, sequence gathers —
        same serving contract as the ring test, different collective."""
        import functools

        from sparkdl_tpu.core import runtime
        from sparkdl_tpu.models.llama import LlamaModel, generate
        from sparkdl_tpu.parallel.ring_attention import ulysses_attention

        cfg, dense_model, v = self._setup()
        # tiny cfg has 4 heads → 4-device sp mesh (subset of the 8)
        mesh = runtime.make_mesh({"sp": 4}, jax.devices()[:4])
        u_model = LlamaModel(cfg, attn_fn=functools.partial(
            ulysses_attention, mesh=mesh, axis="sp"))
        ids = np.random.RandomState(9).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int32)
        ref = np.asarray(generate(dense_model, v, ids, 4))
        got = np.asarray(generate(u_model, v, ids, 4))
        np.testing.assert_array_equal(got, ref)

    def test_sp_attn_fn_indivisible_seq_falls_back(self):
        """A ring attn_fn whose sp axis does not divide the prompt length
        cannot shard the prefill — generate() must fall back to the dense
        path (trace-time), not crash (a working pre-round-4 call must stay
        working)."""
        import functools

        from sparkdl_tpu.core import runtime
        from sparkdl_tpu.models.llama import LlamaModel, generate
        from sparkdl_tpu.parallel.ring_attention import ring_attention

        cfg, dense_model, v = self._setup()
        mesh = runtime.make_mesh({"sp": 8})
        sp_model = LlamaModel(cfg, attn_fn=functools.partial(
            ring_attention, mesh=mesh, axis="sp"))
        ids = np.random.RandomState(8).randint(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)  # 12 % 8 != 0
        ref = np.asarray(generate(dense_model, v, ids, 4))
        got = np.asarray(generate(sp_model, v, ids, 4))
        np.testing.assert_array_equal(got, ref)

    def test_maskless_attn_fn_used_when_unpadded(self):
        """Without pad_lens a maskless attn_fn IS honored at prefill (the
        causal square needs no kv_mask)."""
        from sparkdl_tpu.models.llama import LlamaModel, generate

        cfg, dense_model, v = self._setup()
        calls = []

        def spy_attn(q, k, v_, causal=False):
            calls.append(q.shape)
            from sparkdl_tpu.parallel.ring_attention import dense_attention
            return dense_attention(q, k, v_, causal)

        ids = np.random.RandomState(3).randint(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)
        ref = np.asarray(generate(dense_model, v, ids, 4))
        m = LlamaModel(cfg, attn_fn=spy_attn)
        got = np.asarray(generate(m, v, ids, 4))
        np.testing.assert_array_equal(got, ref)
        # prefill (S=12) went through the fn; decode steps (S=1) did not
        assert calls and all(s[2] == 12 for s in calls)


def test_left_pad_prompts_pad_to():
    from sparkdl_tpu.models.llama import left_pad_prompts

    ids, pads = left_pad_prompts([[1, 2], [3]], pad_to=5)
    assert ids.shape == (2, 5)
    assert list(pads) == [3, 4]
    assert ids[0].tolist() == [0, 0, 0, 1, 2]
    with pytest.raises(ValueError, match="pad_to"):
        left_pad_prompts([[1, 2, 3]], pad_to=2)


def test_generation_udf_eos_across_chunks():
    """EOS trimming composes with the streamed chunked data plane: rows in
    different chunks each get their tail trimmed to one eos."""
    import sparkdl_tpu as sdl
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate
    from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.int32))
    prompt = [1, 2, 3]
    free = np.asarray(generate(model, v, np.asarray([prompt], np.int32), 5))
    eos = int(free[0, 3])

    # 5 identical rows, batchRows=2 → 3 chunks; every row must come back
    # trimmed identically regardless of which chunk carried it
    df = sdl.DataFrame.fromPydict({"p": [prompt] * 5}, numPartitions=3)
    registerGenerationUDF("ec", model, v, max_new_tokens=5, eos_id=eos,
                          batchRows=2)
    try:
        rows = sdl.applyUDF(df, "ec", "p", "c").collect()
    finally:
        unregisterUDF("ec")
    assert len(rows) == 5
    for r in rows:
        assert list(r["c"]) == prompt + [eos]


def test_generation_eos_with_sampling():
    """The while_loop EOS path composes with temperature/top-k/top-p
    sampling: deterministic per key, correct shapes, done rows pinned to
    eos, and the same key reproduces the same tokens."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    kw = dict(temperature=0.9, top_k=20, top_p=0.95,
              rng=jax.random.PRNGKey(7), eos_id=5)
    out1, s1 = generate(model, v, ids, 12, return_steps=True, **kw)
    out2, s2 = generate(model, v, ids, 12, return_steps=True, **kw)
    out1, out2 = np.asarray(out1), np.asarray(out2)
    np.testing.assert_array_equal(out1, out2)  # key-deterministic
    assert s1 == s2 and out1.shape == (2, 15)
    for r in range(2):
        tail = out1[r, 3:]
        if (tail == 5).any():  # once eos appears, it repeats to the end
            first = int(np.argmax(tail == 5))
            assert (tail[first:] == 5).all()


def test_generation_eos_early_exit_stops_decode_steps():
    """Compute-side early stop (round-3 verdict Next #6): a batch whose
    rows all emit eos at step k executes ~k decode-loop iterations, not
    max_new_tokens — and still produces the exact fixed-length output."""
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel, generate

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    v = model.init(jax.random.PRNGKey(0), ids)

    free = np.asarray(generate(model, v, ids, 64))
    # eos = the token every row greedily emits first → done after step 1
    eos_candidates = free[:, 3]
    if eos_candidates[0] == eos_candidates[1]:
        eos = int(eos_candidates[0])
        out, n_steps = generate(model, v, ids, 64, eos_id=eos,
                                return_steps=True)
        assert n_steps <= 2, f"early exit did not fire: {n_steps} steps"
        out = np.asarray(out)
        assert (out[:, 3:] == eos).all()
    # rows finishing at different times: use row 0's first token as eos —
    # the loop must run until the LAST row finishes (or max), and early
    # rows re-emit eos meanwhile
    eos0 = int(free[0, 3])
    out, n_steps = generate(model, v, ids, 64, eos_id=eos0,
                            return_steps=True)
    out = np.asarray(out)
    done_steps = [int(np.argmax(out[r, 3:] == eos0)) + 1
                  if (out[r, 3:] == eos0).any() else 64 for r in range(2)]
    assert n_steps <= min(max(done_steps) + 1, 64)
    # output contract unchanged vs the fixed-length semantics
    assert out.shape == (2, 67)
    ref = np.asarray(generate(model, v, ids, 64))
    for r in range(2):
        k = done_steps[r]
        np.testing.assert_array_equal(out[r, :3 + k], ref[r, :3 + k])
        assert (out[r, 3 + k:] == eos0).all()


def test_cast_float_leaves_mechanics():
    """Matrix float leaves cast to the serving dtype; 1-D float leaves
    (BN stats / norm scales / biases — flax does NOT cast those at use)
    and integer leaves pass through untouched; the cast is idempotent."""
    from sparkdl_tpu.models import cast_float_leaves

    tree = {"w": np.ones((4, 4), np.float32),
            "ids": np.arange(3, dtype=np.int32),
            "nested": {"bn_scale": np.zeros(4, np.float64)}}
    out = cast_float_leaves(tree, "bfloat16")
    assert str(out["w"].dtype) == "bfloat16"
    # 1-D leaf untouched: flax BatchNorm/RMSNorm normalize in f32
    # without casting stats/scale — pre-casting them would shift outputs
    assert out["nested"]["bn_scale"].dtype == np.float64
    assert out["ids"].dtype == np.int32
    np.testing.assert_array_equal(np.asarray(out["ids"]), tree["ids"])
    again = cast_float_leaves(out, "bfloat16")
    assert str(again["w"].dtype) == "bfloat16"
    # opt-in full cast still available
    full = cast_float_leaves(tree, "bfloat16", min_ndim=0)
    assert str(full["nested"]["bn_scale"].dtype) == "bfloat16"


def test_generation_udf_serving_params_dtype():
    """``params_dtype='bfloat16'`` serves from bf16-stored weights (the
    weight-HBM-bandwidth lever for decode): generation runs end-to-end
    with prompts preserved as prefixes, and the bf16-compute model's
    logits with cast weights stay close to the f32-stored ones — flax
    casts params to the compute dtype at use, so bf16-compute modules
    see identical values; only the f32-compute head/norms see
    bf16-rounded weights."""
    import pandas as pd

    import sparkdl_tpu as sdl
    from sparkdl_tpu.models import cast_float_leaves
    from sparkdl_tpu.models.llama import LlamaConfig, LlamaModel
    from sparkdl_tpu.udf import registerGenerationUDF, unregisterUDF

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg, dtype=jnp.bfloat16)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))

    logits_f32 = np.asarray(
        model.apply(variables, jnp.asarray(ids)), np.float32)
    logits_bf16 = np.asarray(model.apply(
        cast_float_leaves(variables, "bfloat16"), jnp.asarray(ids)),
        np.float32)
    scale = max(np.abs(logits_f32).max(), 1.0)
    assert np.abs(logits_bf16 - logits_f32).max() < 0.05 * scale

    prompts = [ids[0, :5].tolist(), ids[1].tolist()]
    df = sdl.DataFrame.fromPandas(pd.DataFrame({"prompt": prompts}))
    registerGenerationUDF("gen_bf16", model, variables, max_new_tokens=4,
                          params_dtype="bfloat16")
    try:
        out = sdl.applyUDF(df, "gen_bf16", "prompt", "c").toPandas()
        for row, prompt in zip(out["c"], prompts):
            assert [int(t) for t in row[:len(prompt)]] == prompt
            assert len(row) == len(prompt) + 4
            assert all(0 <= int(t) < cfg.vocab_size for t in row)
    finally:
        unregisterUDF("gen_bf16")
