"""Graph toolkit tests (reference test models: graph/test_builder.py,
test_input.py, test_pieces.py — equivalence-style, SURVEY.md §4)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.graph import (GraphFunction, IsolatedSession, TFInputGraph,
                               XlaInputGraph, buildFlattener,
                               buildSpImageConverter, load_weights,
                               makeGraphUDF, op_name, tensor_name,
                               validated_input, validated_output)


# ---------------------------------------------------------------- utils ----

def test_name_hygiene():
    assert op_name("x:0") == "x"
    assert op_name("x") == "x"
    assert tensor_name("x") == "x:0"
    assert tensor_name("x:1") == "x:1"
    with pytest.raises(ValueError):
        op_name("bad name!")
    with pytest.raises(TypeError):
        op_name(None)


def test_validated_feeds_fetches():
    assert validated_input("a:0", ["a", "b"]) == "a"
    with pytest.raises(ValueError):
        validated_input("c", ["a", "b"])
    assert validated_output("b", ["a", "b"]) == "b"
    with pytest.raises(ValueError):
        validated_output("z:0", ["a"])


# -------------------------------------------------------- GraphFunction ----

def test_from_jax_and_call():
    g = GraphFunction.fromJax(lambda x: x * 2.0, ["x"], ["y"])
    out = g(x=np.ones((2, 3), np.float32))
    assert np.allclose(out["y"], 2.0)
    # TF-style ":0" spellings accepted
    out2 = g({"x:0": np.ones((2, 3), np.float32)})
    assert np.allclose(out2["y"], 2.0)
    with pytest.raises(ValueError, match="Missing feeds"):
        g({})
    with pytest.raises(ValueError, match="Unknown feeds"):
        g(x=np.ones(3), z=np.ones(3))


def test_multi_output_requires_names():
    with pytest.raises(ValueError, match="declare output_names"):
        GraphFunction.fromJax(lambda x: (x, x * 2), ["x"])(x=np.ones(2))
    g = GraphFunction.fromJax(lambda x: (x + 1, x * 2), ["x"], ["a", "b"])
    out = g(x=np.ones(2, np.float32))
    assert np.allclose(out["a"], 2.0) and np.allclose(out["b"], 2.0)
    g2 = GraphFunction.fromJax(lambda x: {"s": x.sum()}, ["x"], ["s"])
    assert float(g2(x=np.ones(4, np.float32))["s"]) == 4.0


def test_from_list_chains_positionally():
    a = GraphFunction.fromJax(lambda x: x + 1.0, ["x"], ["u"])
    b = GraphFunction.fromJax(lambda u: u * 3.0, ["inp"], ["v"])
    chain = GraphFunction.fromList([a, b])
    assert chain.input_names == ["x"] and chain.output_names == ["v"]
    assert np.allclose(chain(x=np.ones(2, np.float32))["v"], 6.0)
    assert np.allclose(a.then(b)(x=np.ones(2, np.float32))["v"], 6.0)
    two_out = GraphFunction.fromJax(lambda x: (x, x), ["x"], ["p", "q"])
    with pytest.raises(ValueError, match="arity"):
        GraphFunction.fromList([two_out, b])


def test_rename():
    g = GraphFunction.fromJax(lambda x: x * 2.0, ["x"], ["y"])
    r = g.rename(inputs={"x": "image"}, outputs={"y": "features"})
    assert r.input_names == ["image"] and r.output_names == ["features"]
    assert np.allclose(r(image=np.ones(2, np.float32))["features"], 2.0)


def test_serialize_roundtrip_symbolic_batch(tmp_path):
    g = GraphFunction.fromJax(lambda x: jnp.tanh(x @ jnp.ones((3, 2))),
                              ["x"], ["y"])
    path = os.path.join(tmp_path, "g.gfn")
    g.dump(path, {"x": ((None, 3), "float32")})
    g2 = GraphFunction.load(path)
    assert g2.input_names == ["x"] and g2.output_names == ["y"]
    for n in (1, 4, 7):  # symbolic batch dim: any size works
        x = np.random.RandomState(n).randn(n, 3).astype(np.float32)
        assert np.allclose(g2(x=x)["y"], g(x=x)["y"], atol=1e-6)
    with pytest.raises(ValueError, match="serialize needs input_specs"):
        GraphFunction.fromJax(lambda x: x, ["x"], ["y"]).serialize()
    with pytest.raises(ValueError, match="Not a serialized"):
        GraphFunction.deserialize(b"junk")


def test_serialize_independent_variable_dims():
    # leading None dims share the batch symbol; other None dims are each
    # independent — batch != height must work after a roundtrip
    g = GraphFunction.fromJax(lambda x: x.sum(axis=(1, 2)), ["x"], ["y"])
    blob = g.serialize({"x": ((None, None, 3), "float32")})
    g2 = GraphFunction.deserialize(blob)
    x = np.ones((2, 7, 3), np.float32)  # batch=2, height=7: distinct
    assert np.allclose(g2(x=x)["y"], 21.0)


def test_jit_and_single_output_adapter():
    g = GraphFunction.fromJax(lambda x: x - 1.0, ["x"], ["y"])
    jitted = g.jit()
    x = np.ones((3,), np.float32)
    assert np.allclose(jitted(x=x)["y"], 0.0)
    fn = g.as_single_output_fn()
    assert np.allclose(fn(x), 0.0)
    multi = GraphFunction.fromJax(lambda a, b: a + b, ["a", "b"], ["y"])
    with pytest.raises(ValueError, match="exactly one input"):
        multi.as_single_output_fn()


# ------------------------------------------------------ IsolatedSession ----

def test_isolated_session_build_run_export():
    with IsolatedSession() as issn:
        x = issn.placeholder((None, 3), "float32", name="x")
        w = issn.constant(np.full((3,), 2.0, np.float32), name="w")
        z = issn.apply(jnp.tanh, x * w + 1.0, name="z")
        gfn = issn.asGraphFunction([x], [z])
    v = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    expect = np.tanh(v * 2.0 + 1.0)
    # eager run (Session.run analogue)
    assert np.allclose(issn.run(z, {"x": v}), expect, atol=1e-6)
    # exported artifact
    assert np.allclose(gfn(x=v)["z"], expect, atol=1e-6)
    assert gfn.input_names == ["x"] and gfn.output_names == ["z"]


def test_isolated_session_operators():
    with IsolatedSession() as issn:
        a = issn.placeholder((None,), name="a")
        b = issn.placeholder((None,), name="b")
        exprs = [a + b, a - b, a * b, a / b, -a, 1.0 + a, 2.0 * b,
                 3.0 - a, 6.0 / b, a[0]]
        gfn = issn.asGraphFunction([a, b], exprs)
    av = np.array([2.0, 4.0], np.float32)
    bv = np.array([1.0, 2.0], np.float32)
    out = gfn(a=av, b=bv)
    vals = [out[n] for n in gfn.output_names]
    for got, want in zip(vals, [av + bv, av - bv, av * bv, av / bv, -av,
                                1 + av, 2 * bv, 3 - av, 6 / bv, av[0]]):
        assert np.allclose(got, want)


def test_import_graph_function_splices():
    inner = GraphFunction.fromJax(lambda x: x * 10.0, ["x"], ["y"])
    with IsolatedSession() as issn:
        a = issn.placeholder((None,), name="a")
        mid = issn.apply(lambda t: t + 1.0, a)
        outs = issn.importGraphFunction(inner, [mid], prefix="sub")
        gfn = issn.asGraphFunction([a], outs)
    assert np.allclose(gfn(a=np.ones(2, np.float32))[gfn.output_names[0]],
                       20.0)
    with pytest.raises(ValueError, match="expects 1 inputs"):
        with IsolatedSession() as issn:
            a = issn.placeholder((None,), name="a")
            issn.importGraphFunction(inner, [a, a])


def test_cross_session_nodes_rejected():
    with IsolatedSession() as s1:
        a = s1.placeholder((None,), name="a")
    with IsolatedSession() as s2:
        with pytest.raises(ValueError, match="another session"):
            s2.apply(jnp.tanh, a)


def test_non_placeholder_input_rejected():
    with IsolatedSession() as issn:
        a = issn.placeholder((None,), name="a")
        z = issn.apply(jnp.tanh, a)
        with pytest.raises(ValueError, match="not a placeholder"):
            issn.asGraphFunction([z], [z])


# --------------------------------------------------------------- pieces ----

def test_sp_image_converter_bgr_and_rescale():
    conv = buildSpImageConverter("BGR", scale=1 / 127.5, offset=-1.0)
    x = np.random.RandomState(0).randint(0, 256, (2, 5, 5, 3)).astype(np.uint8)
    out = np.asarray(conv(image=x)["converted"])
    want = x[..., ::-1].astype(np.float32) / 127.5 - 1.0
    assert np.allclose(out, want, atol=1e-6)
    # RGB passthrough, no rescale
    conv2 = buildSpImageConverter("RGB")
    assert np.allclose(np.asarray(conv2(image=x)["converted"]),
                       x.astype(np.float32))


def test_flattener_and_composed_pipeline():
    conv = buildSpImageConverter("BGR")
    flat = buildFlattener("converted", "flattened")
    chain = GraphFunction.fromList([conv, flat])
    x = np.random.RandomState(1).randint(0, 256, (3, 4, 4, 3)).astype(np.uint8)
    out = np.asarray(chain(image=x)["flattened"])
    assert out.shape == (3, 48)
    assert np.allclose(out, x[..., ::-1].reshape(3, -1).astype(np.float32))


# -------------------------------------------------------- XlaInputGraph ----

def test_from_graph_and_from_graph_function():
    ig = XlaInputGraph.fromGraph(lambda x: x * 2.0, ["x"], ["y"])
    assert np.allclose(
        ig.translateToGraphFunction()(x=np.ones(2, np.float32))["y"], 2.0)
    assert TFInputGraph is XlaInputGraph
    g = GraphFunction.fromJax(lambda x: x, ["x"], ["y"])
    assert XlaInputGraph.fromGraphFunction(g).asGraphFunction() is g


def test_from_serialized(tmp_path):
    g = GraphFunction.fromJax(lambda x: x + 5.0, ["x"], ["y"])
    blob = g.serialize({"x": ((None,), "float32")})
    ig = XlaInputGraph.fromSerialized(blob)
    assert np.allclose(
        ig.translateToGraphFunction()(x=np.zeros(3, np.float32))["y"], 5.0)
    p = os.path.join(tmp_path, "g.gfn")
    g.dump(p, {"x": ((None,), "float32")})
    ig2 = XlaInputGraph.fromSerialized(p)
    assert ig2.output_names == ["y"]


def test_from_keras_equivalence():
    keras = pytest.importorskip("keras")
    model = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(4, activation="tanh"),
        keras.layers.Dense(2),
    ])
    ig = XlaInputGraph.fromKeras(model)
    x = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    got = np.asarray(ig.translateToGraphFunction()(input=x)["output"])
    want = np.asarray(model(x))
    assert np.allclose(got, want, atol=1e-5)


def test_from_flax():
    import flax.linen as nn
    import jax

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    m = Tiny()
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    ig = XlaInputGraph.fromFlax(m, variables)
    x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    got = np.asarray(ig.translateToGraphFunction()(input=x)["output"])
    assert np.allclose(got, np.asarray(m.apply(variables, x)), atol=1e-6)


@pytest.fixture(scope="module")
def tf():
    return pytest.importorskip("tensorflow")


def test_from_saved_model(tf, tmp_path):
    class M(tf.Module):
        def __init__(self):
            super().__init__()
            self.w = tf.Variable(tf.ones((3, 2)))

        @tf.function(input_signature=[
            tf.TensorSpec([None, 3], tf.float32, name="x")])
        def __call__(self, x):
            return {"y": tf.matmul(x, self.w) + 1.0}

    path = os.path.join(tmp_path, "sm")
    tf.saved_model.save(M(), path)
    ig = XlaInputGraph.fromSavedModel(path)
    assert ig.input_names == ["x"] and ig.output_names == ["y"]
    x = np.ones((2, 3), np.float32)
    assert np.allclose(ig.translateToGraphFunction()(x=x)["y"], 4.0)
    with pytest.raises(ValueError, match="no signature"):
        XlaInputGraph.fromSavedModel(path, signature="nope")
    ig2 = XlaInputGraph.fromSavedModelWithSignature(path, "serving_default")
    assert ig2.output_names == ["y"]
    # feed/fetch names bind BY NAME against signature keys, never position
    with pytest.raises(ValueError, match="not a signature input"):
        XlaInputGraph.fromSavedModel(path, feed_names=["wrong"])
    with pytest.raises(ValueError, match="not a signature output"):
        XlaInputGraph.fromSavedModel(path, fetch_names=["nope"])


def test_from_saved_model_fetch_selection_by_name(tf, tmp_path):
    class M2(tf.Module):
        @tf.function(input_signature=[
            tf.TensorSpec([None, 2], tf.float32, name="x")])
        def __call__(self, x):
            # alphabetical order is (logits, probs); select 'probs' by name
            return {"logits": x * 10.0, "probs": x * 0.1}

    path = os.path.join(tmp_path, "sm2")
    tf.saved_model.save(M2(), path)
    ig = XlaInputGraph.fromSavedModel(path, fetch_names=["probs"])
    x = np.ones((2, 2), np.float32)
    out = ig.translateToGraphFunction()(x=x)
    assert list(out) == ["probs"]
    assert np.allclose(out["probs"], 0.1)


def test_from_graph_def(tf):
    with tf.Graph().as_default() as g:
        xin = tf.compat.v1.placeholder(tf.float32, [None, 3], name="xin")
        tf.identity(xin * 2.0 + 0.5, name="yout")
    ig = XlaInputGraph.fromGraphDef(g.as_graph_def(), ["xin"], ["yout"])
    x = np.ones((2, 3), np.float32)
    out = ig.translateToGraphFunction()(xin=x)["yout"]
    assert np.allclose(out, 2.5)
    # serialized proto bytes accepted too
    ig2 = XlaInputGraph.fromGraphDef(
        g.as_graph_def().SerializeToString(), ["xin:0"], ["yout:0"])
    assert np.allclose(
        ig2.translateToGraphFunction()(xin=x)["yout"], 2.5)


# ------------------------------------------------------- weight loading ----

def test_load_weights_npz(tmp_path):
    p = os.path.join(tmp_path, "w.npz")
    np.savez(p, **{"layer1.kernel": np.ones((2, 2)),
                   "layer1.bias": np.zeros(2)})
    tree = load_weights(p)
    assert set(tree["layer1"]) == {"kernel", "bias"}


def test_load_weights_safetensors(tmp_path):
    st = pytest.importorskip("safetensors.numpy")
    p = os.path.join(tmp_path, "w.safetensors")
    # both separators appear in the wild ("/" is what this repo's own
    # safetensors writers emit)
    st.save_file({"a.b": np.arange(4, dtype=np.float32),
                  "Dense_0/kernel": np.ones((2, 2), np.float32)}, p)
    tree = load_weights(p)
    assert np.allclose(tree["a"]["b"], np.arange(4))
    assert tree["Dense_0"]["kernel"].shape == (2, 2)


def test_load_weights_h5(tmp_path):
    h5py = pytest.importorskip("h5py")
    p = os.path.join(tmp_path, "w.h5")
    with h5py.File(p, "w") as f:
        f.create_dataset("dense/kernel", data=np.ones((3, 3)))
    tree = load_weights(p)
    assert tree["dense"]["kernel"].shape == (3, 3)


def test_load_weights_tf_checkpoint(tf, tmp_path):
    v = tf.Variable(np.full((2,), 7.0, np.float32), name="my/var")
    ckpt = tf.train.Checkpoint(v=v)
    prefix = ckpt.write(os.path.join(tmp_path, "ck"))
    tree = load_weights(prefix)
    flat = []

    def walk(node):
        for val in node.values():
            (walk if isinstance(val, dict) else
             lambda x: flat.append(np.asarray(x)))(val)
    walk(tree)
    assert any(a.shape == (2,) and np.allclose(a, 7.0) for a in flat)


def test_load_weights_unknown(tmp_path):
    with pytest.raises(ValueError, match="Cannot determine"):
        load_weights(os.path.join(tmp_path, "nothing.xyz"))


def test_from_checkpoint_binds_model_fn(tmp_path):
    p = os.path.join(tmp_path, "w.npz")
    np.savez(p, **{"w": np.full((3, 2), 2.0, np.float32)})
    ig = XlaInputGraph.fromCheckpoint(
        p, lambda params, batch: batch @ params["w"])
    x = np.ones((2, 3), np.float32)
    assert np.allclose(
        ig.translateToGraphFunction()(input=x)["output"], 6.0)


# ---------------------------------------------------------- makeGraphUDF ----

def test_make_graph_udf_end_to_end():
    from sparkdl_tpu import DataFrame
    from sparkdl_tpu.udf import applyUDF, unregisterUDF

    gfn = GraphFunction.fromJax(lambda x: x * 3.0, ["x"], ["y"])
    makeGraphUDF(gfn, "triple")
    try:
        df = DataFrame.fromPandas(
            __import__("pandas").DataFrame(
                {"v": [np.ones(2, np.float32) * i for i in range(4)]}))
        out = applyUDF(df, "triple", "v", "tripled").toPandas()
        assert np.allclose(np.stack(out["tripled"].to_numpy()),
                           np.stack([np.ones(2) * 3 * i for i in range(4)]))
    finally:
        unregisterUDF("triple")


def test_make_graph_udf_kinds():
    from sparkdl_tpu.udf import listUDFs, unregisterUDF
    try:
        makeGraphUDF(lambda x: x + 1, "callable_udf")
        blob = GraphFunction.fromJax(lambda x: x, ["x"], ["y"]).serialize(
            {"x": ((None,), "float32")})
        makeGraphUDF(blob, "blob_udf")
        assert {"callable_udf", "blob_udf"} <= set(listUDFs())
        # a bare-string fetches must mean the fetch name, not its first char
        g3 = GraphFunction.fromJax(lambda x: {"probs": x}, ["x"], ["probs"])
        makeGraphUDF(g3, "str_fetch_udf", fetches="probs")
        with pytest.raises(TypeError, match="asGraphFunction"):
            makeGraphUDF(IsolatedSession(), "bad")
        with pytest.raises(TypeError, match="Cannot make a UDF"):
            makeGraphUDF(123, "bad")
    finally:
        unregisterUDF("callable_udf")
        unregisterUDF("blob_udf")


def test_image_input_placeholder_and_utils():
    from sparkdl_tpu.transformers.utils import (IMAGE_INPUT_PLACEHOLDER_NAME,
                                                imageInputPlaceholder,
                                                imageInputSpec)
    from sparkdl_tpu.utils import Timer, flatten_with_paths, tree_size_bytes

    node = imageInputPlaceholder(3, 8, 8)
    issn = node.session
    out = issn.apply(lambda b: b.reshape(b.shape[0], -1), node)
    gfn = issn.asGraphFunction([node], [out])
    x = np.random.RandomState(0).rand(2, 8, 8, 3).astype(np.float32)
    res = gfn({IMAGE_INPUT_PLACEHOLDER_NAME: x})
    assert res[out.name].shape == (2, 192)
    blob = gfn.serialize(imageInputSpec(8, 8))
    assert GraphFunction.deserialize(blob)(
        {IMAGE_INPUT_PLACEHOLDER_NAME: x})[out.name].shape == (2, 192)

    tree = {"a": {"b": np.zeros((2, 2), np.float32)}, "c": np.zeros(3)}
    assert dict(flatten_with_paths(tree))["a/b"].shape == (2, 2)
    assert tree_size_bytes(tree) == 2 * 2 * 4 + 3 * 8
    with Timer() as t:
        pass
    assert t.seconds >= 0.0


def test_as_graph_function_validates_placeholders_at_export():
    """An output depending on an undeclared placeholder must fail at
    asGraphFunction (export) time, not with 'No feed provided' at call time
    (ADVICE r1 item 4)."""
    import sparkdl_tpu as sdl
    with sdl.IsolatedSession() as issn:
        x = issn.placeholder(name="x")
        y = issn.placeholder(name="y")
        z = x + y
        with pytest.raises(ValueError, match=r"placeholder.*'y'"):
            issn.asGraphFunction([x], [z])
        gfn = issn.asGraphFunction([x, y], [z])  # declared: fine
        out = gfn({"x": np.ones(2, np.float32), "y": np.ones(2, np.float32)})
        np.testing.assert_allclose(out[gfn.output_names[0]], 2.0)


def test_probe_output_names_via_eval_shape():
    """With input_specs, undeclared multi-output fns fail at construction;
    dict returns get their keys as output names (round-2 verdict weak #8)."""
    from sparkdl_tpu.graph.function import GraphFunction

    specs = {"input": ((None, 3), "float32")}
    # dict return: names inferred abstractly, no compute
    gfn = GraphFunction.fromJax(
        lambda x: {"a": x * 2, "b": x + 1}, input_specs=specs)
    assert gfn.output_names == ["a", "b"]

    # undeclared tuple multi-output: construction-time error
    with pytest.raises(ValueError, match="declare output_names"):
        GraphFunction.fromJax(lambda x: (x, x * 2), input_specs=specs)

    # without specs: permissive default, error still surfaces at call
    gfn2 = GraphFunction.fromJax(lambda x: (x, x * 2))
    assert gfn2.output_names == ["output"]
    with pytest.raises(ValueError):
        gfn2({"input": np.ones((2, 3), np.float32)})
