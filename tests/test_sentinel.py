"""Online anomaly sentinel tests (ISSUE 17): rolling-baseline drift
detection over step-time/TTFT/decode/queue-depth — a 5x slowdown must
fire an ``anomaly`` flight-recorder event + counter within one rolling
window, the baseline must NOT absorb anomalous samples (a sustained
slowdown can't normalize itself), and with the sentinel off the hook is
pinned ≈ free (PR 6's plane-off rule). Jax-free throughout.
"""

import json
import os
import threading

import pytest

from sparkdl_tpu.runner import events, sentinel, telemetry
from sparkdl_tpu.runner.metrics import ThroughputMeter


@pytest.fixture(autouse=True)
def _fresh():
    """Every test starts disarmed with clean recorder/registry; env
    arming from one test must not leak into the next."""
    sentinel.disarm()
    telemetry.reset()
    events.reset()
    yield
    sentinel.disarm()
    telemetry.reset()
    events.reset()


class TestRollingBaseline:
    def test_detects_5x_slowdown_within_one_window(self):
        b = sentinel.RollingBaseline("step_time", ratio=2.0, window=8,
                                     min_n=8)
        for _ in range(16):
            assert b.observe(0.01) is None  # healthy: builds baseline
        fired = []
        for i in range(8):  # one window of 5x-slow steps
            a = b.observe(0.05)
            if a:
                fired.append((i, a))
        assert len(fired) == 1  # edge-triggered: ONE event per episode
        i, a = fired[0]
        assert i < 8  # detected within one rolling window
        assert a["metric"] == "step_time"
        assert a["window_p95"] >= 0.05
        assert a["baseline_p95"] == pytest.approx(0.01)

    def test_anomalous_samples_do_not_poison_baseline(self):
        """A sustained slowdown must keep reading as anomalous — if the
        slow samples were absorbed, the baseline would drift up and the
        episode would self-normalize."""
        b = sentinel.RollingBaseline("m", ratio=2.0, window=8, min_n=8)
        for _ in range(16):
            b.observe(0.01)
        n_before = len(b._baseline)
        for _ in range(50):
            b.observe(0.05)
        assert len(b._baseline) == n_before  # nothing absorbed
        assert b.summary()["anomalous"] is True
        assert b.baseline_p95() == pytest.approx(0.01)

    def test_recovery_rearms_the_edge(self):
        b = sentinel.RollingBaseline("m", ratio=2.0, window=4, min_n=8)
        for _ in range(16):
            b.observe(0.01)
        assert any(b.observe(0.05) for _ in range(4))  # episode 1
        healthy = [b.observe(0.01) for _ in range(8)]  # full recovery
        assert not any(healthy)
        assert b.summary()["anomalous"] is False
        assert any(b.observe(0.05) for _ in range(4))  # episode 2 fires
        assert b.summary()["anomalies"] == 2

    def test_zero_baseline_never_divides_or_fires(self):
        """An all-zero baseline (idle queue depth) must not fire on the
        first nonzero sample — ratio-vs-zero is not drift evidence."""
        b = sentinel.RollingBaseline("queue_depth", ratio=2.0, window=4,
                                     min_n=8)
        for _ in range(16):
            assert b.observe(0.0) is None
        for _ in range(8):
            assert b.observe(3.0) is None


class TestSentinelPlane:
    def test_anomaly_emits_event_and_counter(self):
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        for _ in range(16):
            sentinel.observe("step_time", 0.01)
        for _ in range(8):
            sentinel.observe("step_time", 0.05)
        anomalies = [e for e in events.get_recorder().tail()
                     if e["name"] == "anomaly"]
        assert len(anomalies) == 1
        assert anomalies[0]["metric"] == "step_time"
        assert anomalies[0]["ph"] == "P"
        counters = telemetry.registry().snapshot()["counters"]
        assert counters["sentinel_anomalies_total"] == 1
        assert sentinel.anomaly_counts() == {"step_time": 1}

    def test_metrics_are_independent(self):
        """Drift in one metric must not consume another's baseline."""
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        for _ in range(16):
            sentinel.observe("ttft", 0.01)
            sentinel.observe("decode_step", 0.002)
        for _ in range(8):
            sentinel.observe("ttft", 0.05)
            sentinel.observe("decode_step", 0.002)  # still healthy
        assert sentinel.anomaly_counts() == {"ttft": 1}
        st = sentinel.stats()
        assert st["decode_step"]["anomalies"] == 0

    def test_throughput_meter_feeds_step_time(self, monkeypatch):
        """The fit()-side hook: a metered loop whose steps suddenly run
        5x slower must trip the sentinel through ThroughputMeter alone."""
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        now = [100.0]
        monkeypatch.setattr("sparkdl_tpu.runner.metrics.time.perf_counter",
                            lambda: now[0])
        meter = ThroughputMeter(warmup_steps=0)
        for _ in range(20):
            now[0] += 0.01
            meter.update(8)
        for _ in range(8):
            now[0] += 0.05  # injected 5x slowdown
            meter.update(8)
        assert sentinel.anomaly_counts().get("step_time") == 1

    def test_arm_from_env_and_knobs(self, monkeypatch):
        monkeypatch.delenv(sentinel.SENTINEL_ENV, raising=False)
        assert sentinel.maybe_arm_from_env() is None
        assert not sentinel.armed()
        monkeypatch.setenv(sentinel.SENTINEL_ENV, "1")
        monkeypatch.setenv(sentinel.RATIO_ENV, "3.5")
        monkeypatch.setenv(sentinel.WINDOW_ENV, "16")
        monkeypatch.setenv(sentinel.MIN_N_ENV, "10")
        s = sentinel.maybe_arm_from_env()
        assert s is not None and sentinel.armed()
        assert s.ratio == 3.5 and s.window == 16 and s.min_n == 10

    def test_bad_env_values_degrade_to_defaults(self, monkeypatch):
        monkeypatch.setenv(sentinel.SENTINEL_ENV, "1")
        monkeypatch.setenv(sentinel.RATIO_ENV, "fast")
        monkeypatch.setenv(sentinel.WINDOW_ENV, "abc")
        s = sentinel.maybe_arm_from_env()
        assert s is not None
        assert s.ratio == sentinel._DEFAULT_RATIO
        assert s.window == sentinel._DEFAULT_WINDOW
        # a hostile window value still leaves a judgeable deque
        rb = sentinel.RollingBaseline("m", ratio=2.0, window=-3, min_n=4)
        for _ in range(16):
            rb.observe(0.01)
        assert rb.observe(0.05) is not None  # clamped, still detects


class TestOffIsFree:
    def test_off_registers_nothing(self):
        """ISSUE 17 acceptance: with the sentinel off, the same slowdown
        registers nothing — no events, no counters, no state."""
        for _ in range(16):
            sentinel.observe("step_time", 0.01)
        for _ in range(8):
            sentinel.observe("step_time", 0.05)
        assert sentinel._SENTINEL is None  # no state was ever built
        assert sentinel.anomaly_counts() == {}
        assert not any(e["name"] == "anomaly"
                       for e in events.get_recorder().tail())
        assert "sentinel_anomalies_total" not in \
            telemetry.registry().snapshot()["counters"]

    def test_off_adds_no_per_step_overhead(self):
        """The hot-path pin (PR 6's rule): disarmed observe() is one
        global read + return — no lock, no dict, no allocation. Pinned
        structurally: the fast path must bail before any attribute
        access on a Sentinel instance."""
        import dis
        ops = list(dis.get_instructions(sentinel.observe))
        idx = next(i for i, op in enumerate(ops)
                   if op.argval == "_SENTINEL")
        # nothing executes before the disarmed None-check's global read
        assert not any("CALL" in op.opname for op in ops[:idx])

    def test_disarm_after_arm_really_disarms(self):
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        assert sentinel.armed()
        sentinel.disarm()
        assert not sentinel.armed()
        sentinel.observe("step_time", 99.0)
        assert sentinel.anomaly_counts() == {}


class TestBenchLedger:
    def test_anomaly_counts_shape_rides_failure_stats(self):
        """bench.py embeds anomaly_counts() under
        failure_stats.sentinel_anomalies — the shape must stay a flat
        {metric: int} json-serializable dict."""
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        for _ in range(16):
            sentinel.observe("ttft", 0.01)
        for _ in range(8):
            sentinel.observe("ttft", 0.05)
        counts = sentinel.anomaly_counts()
        assert counts == json.loads(json.dumps(counts))
        assert all(isinstance(k, str) and isinstance(v, int)
                   for k, v in counts.items())


class TestConcurrency:
    def test_concurrent_observe_is_safe(self):
        """submit() threads and the engine loop observe concurrently —
        total anomaly accounting must survive the race."""
        sentinel.arm(ratio=2.0, window=8, min_n=8)
        for _ in range(32):
            sentinel.observe("queue_depth", 1.0)

        def hammer():
            for _ in range(200):
                sentinel.observe("queue_depth", 5.0)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # exactly one edge-triggered anomaly for the sustained episode
        assert sentinel.anomaly_counts() == {"queue_depth": 1}
