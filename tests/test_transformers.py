"""Transformer-layer tests: XlaImageTransformer, named models, tensor, UDFs.

Uses ResNet18 at reduced spatial size where possible to stay fast on the CPU
test mesh; equivalence tests compare the pipeline path against direct jitted
calls (the reference's golden-value strategy, SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sparkdl_tpu as sdl
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.models import get_model
from sparkdl_tpu.transformers.tensor import columnToNdarray


def image_df(n=6, h=40, w=40, parts=2, seed=0):
    rng = np.random.default_rng(seed)
    imgs = [rng.integers(0, 256, (h, w, 3), np.uint8) for i in range(n)]
    structs = [imageIO.imageArrayToStruct(im, origin=f"mem://{i}")
               for i, im in enumerate(imgs)]
    import pyarrow as pa
    table = pa.table({"image": pa.array(structs, type=imageIO.imageSchema),
                      "label": pa.array([i % 2 for i in range(n)])})
    return sdl.DataFrame.fromArrow(table, numPartitions=parts), imgs


def test_xla_image_transformer_equivalence():
    df, imgs = image_df()
    fn = lambda b: jnp.mean(b, axis=(1, 2))  # (N,H,W,3) -> (N,3)
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="feat", fn=fn,
                                inputSize=(16, 16), batchSize=4)
    out = t.transform(df)
    got = np.asarray([r.feat for r in out.collect()], dtype=np.float32)
    # direct path: same resize convention (antialiased bilinear — the native
    # packer and jax.image.resize agree in float). The feed path ships uint8
    # over the host→device link (round-3 perf fix), so resized pixels are
    # rounded to the nearest level before the model: tolerance 0.5 level.
    nhwc = np.stack([np.asarray(jax.image.resize(
        im[:, :, ::-1].astype(np.float32), (16, 16, 3), method="bilinear"))
        for im in imgs])
    want = np.asarray(fn(jnp.asarray(nhwc)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=0.75)


def test_xla_image_transformer_alias_and_image_output():
    assert sdl.TFImageTransformer is sdl.XlaImageTransformer
    df, _ = image_df(n=3, parts=1)
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b * 0.5,
        inputSize=(8, 8), batchSize=2, outputMode="image")
    rows = t.transform(df).collect()
    assert rows[0].out["height"] == 8 and rows[0].out["nChannels"] == 3


def test_xla_image_transformer_streams_decode_per_chunk(monkeypatch):
    """Peak host memory is O(batchSize): the Arrow→NHWC decode inside the
    transform op must never materialize more rows than batchSize at once,
    however large the partition (round-1 verdict weak #4)."""
    seen = []
    orig = imageIO.imageColumnFeed

    def spy(column, *a, **kw):
        seen.append(len(column))
        return orig(column, *a, **kw)

    monkeypatch.setattr(imageIO, "imageColumnFeed", spy)
    # the spy must observe every decode: pin the thread backend (a
    # process-pool child's calls would be invisible to the parent's spy;
    # the chunking invariant itself is backend-independent)
    monkeypatch.setenv("SPARKDL_DECODE_BACKEND", "thread")
    df, _ = image_df(n=40, h=8, w=8, parts=1)  # one big partition
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="feat",
                                fn=lambda b: jnp.mean(b, axis=(1, 2)),
                                inputSize=(8, 8), batchSize=8)
    rows = t.transform(df).collect()
    assert len(rows) == 40
    assert seen and max(seen) <= 8


def test_xla_image_transformer_streams_output_per_chunk(monkeypatch):
    """Output-side twin of the decode-streaming test (round-3 verdict
    Next #8): device results convert to their final Arrow form chunk by
    chunk — the full-partition float32 output never materializes. The
    struct/array builders must only ever see <= batchSize rows, and
    image-mode output must round-trip correctly across chunks."""
    from sparkdl_tpu.transformers import xla_image as xi

    seen_structs, seen_arrays = [], []
    orig_structs = imageIO.nhwcToStructs
    orig_arrays = xi.arrayColumnToArrow

    def spy_structs(batch, *a, **kw):
        seen_structs.append(len(batch))
        return orig_structs(batch, *a, **kw)

    def spy_arrays(result):
        seen_arrays.append(len(result))
        return orig_arrays(result)

    monkeypatch.setattr(imageIO, "nhwcToStructs", spy_structs)
    monkeypatch.setattr(xi, "arrayColumnToArrow", spy_arrays)

    df, imgs = image_df(n=20, h=8, w=8, parts=1)  # one big partition
    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="out", fn=lambda b: b * 0.5,
        inputSize=(8, 8), batchSize=4, outputMode="image")
    rows = t.transform(df).collect()
    assert len(rows) == 20
    assert seen_structs and max(seen_structs) <= 4
    assert rows[7].out["height"] == 8 and rows[7].out["nChannels"] == 3

    tv = sdl.XlaImageTransformer(
        inputCol="image", outputCol="feat",
        fn=lambda b: jnp.mean(b, axis=(1, 2)),
        inputSize=(8, 8), batchSize=4)
    got = np.asarray([r.feat for r in tv.transform(df).collect()])
    assert got.shape == (20, 3)
    assert seen_arrays and max(seen_arrays) <= 4


def test_deep_image_featurizer_resnet18_and_persistence(tmp_path):
    df, imgs = image_df(n=4, parts=2)
    f = sdl.DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="ResNet18", batchSize=2, seed=7)
    out = f.transform(df)
    feats = np.asarray([r.features for r in out.collect()], dtype=np.float32)
    assert feats.shape == (4, 512)
    assert f.featureDim() == 512

    # equivalence: direct jitted apply mirroring the fused feed (ISSUE 7).
    # The transform ships the native-size uint8 batch and the compiled
    # prologue does cast → BGR→RGB flip → jax.image.resize on device, so
    # the reference decodes at native size (exact: pack + flip, no host
    # resize) and resizes the same way.
    m = get_model("ResNet18")
    variables = f._load_variables()
    native = imageIO.structsToNHWC(
        [imageIO.imageArrayToStruct(im) for im in imgs], 40, 40,
        dtype=np.uint8).astype(np.float32)
    resized = jax.image.resize(
        jnp.asarray(native), (len(imgs), 224, 224, 3), method="bilinear")
    direct = np.asarray(jax.jit(m.apply_fn(features_only=True))(
        variables, resized))
    np.testing.assert_allclose(feats, direct, rtol=2e-4, atol=2e-4)

    # persistence: weights travel with the transformer
    p = str(tmp_path / "feat")
    f.save(p)
    loaded = sdl.load(p)
    out2 = loaded.transform(df)
    feats2 = np.asarray([r.features for r in out2.collect()], np.float32)
    np.testing.assert_allclose(feats2, feats, rtol=1e-5, atol=1e-5)


def test_deep_image_predictor_decode():
    df, _ = image_df(n=3, parts=1)
    p = sdl.DeepImagePredictor(inputCol="image", outputCol="pred",
                               modelName="ResNet18", batchSize=4,
                               decodePredictions=True, topK=3)
    rows = p.transform(df).collect()
    assert len(rows[0].pred) == 3
    assert {"class", "label", "score"} <= set(rows[0].pred[0])
    scores = [e["score"] for e in rows[0].pred]
    assert scores == sorted(scores, reverse=True)


def test_xla_transformer_vector_column():
    df = sdl.DataFrame.fromPydict(
        {"x": [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]}, numPartitions=2)
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b @ jnp.array([[1.0], [10.0]]),
                           batchSize=2)
    out = t.transform(df)
    ys = [r.y for r in out.collect()]
    assert [y[0] for y in ys] == [21.0, 43.0, 65.0]


def test_column_to_ndarray_ragged_raises():
    import pyarrow as pa
    col = pa.array([[1.0, 2.0], [3.0]])
    with pytest.raises(ValueError, match="Ragged"):
        columnToNdarray(col, None)


def test_keras_transformer_and_image_file_transformer(tmp_path):
    keras = pytest.importorskip("keras")
    if keras.backend.backend() != "jax":
        pytest.skip("keras not on jax backend")
    model_file = str(tmp_path / "m.keras")
    m = keras.Sequential([keras.layers.Input((3,)),
                          keras.layers.Dense(2, use_bias=False)])
    m.save(model_file)
    w = np.asarray(m.layers[0].kernel.value)

    df = sdl.DataFrame.fromPydict({"x": [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]})
    t = sdl.KerasTransformer(inputCol="x", outputCol="y",
                             modelFile=model_file, batchSize=2)
    ys = np.asarray([r.y for r in t.transform(df).collect()], np.float32)
    np.testing.assert_allclose(ys, w[:2], rtol=1e-5)

    # image-file path: tiny keras conv model over loaded PNGs
    from PIL import Image
    rng = np.random.default_rng(0)
    uris = []
    for i in range(3):
        f = str(tmp_path / f"im{i}.png")
        Image.fromarray(rng.integers(0, 256, (10, 10, 3), np.uint8)).save(f)
        uris.append(f)
    im_model_file = str(tmp_path / "imm.keras")
    im_model = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.GlobalAveragePooling2D()])
    im_model.save(im_model_file)
    kt = sdl.KerasImageFileTransformer(
        inputCol="uri", outputCol="out", modelFile=im_model_file,
        imageLoader=sdl.transformers.defaultImageLoader((8, 8)), batchSize=2)
    udf_df = sdl.DataFrame.fromPydict({"uri": uris})
    rows = kt.transform(udf_df).collect()
    assert len(rows) == 3 and len(rows[0].out) == 3


def test_compat_aliases_and_direct_image_udf():
    """Pin the reference-compat surface: the TF-era names are aliases,
    and registerImageUDF works standalone (not only through
    registerKerasImageUDF)."""
    assert sdl.TFTransformer is sdl.XlaTransformer
    assert isinstance(sdl.__version__, str) and sdl.__version__

    df, _ = image_df(n=3, parts=1)
    sdl.registerImageUDF("half8", lambda b: jnp.mean(b, axis=(1, 2)),
                         inputSize=(8, 8), batchSize=2)
    try:
        out = sdl.applyUDF(df, "half8", "image", "m")
        rows = out.collect()
        assert len(rows) == 3 and len(rows[0]["m"]) == 3  # mean per channel
    finally:
        sdl.udf.unregisterUDF("half8")


def test_udf_registry_roundtrip():
    sdl.registerUDF("double_it", lambda b: b * 2.0, batchSize=4)
    assert "double_it" in sdl.listUDFs()
    df = sdl.DataFrame.fromPydict({"x": [[1.0], [2.0]]})
    out = sdl.applyUDF(df, "double_it", "x", "y")
    assert [r.y[0] for r in out.collect()] == [2.0, 4.0]
    with pytest.raises(ValueError, match="not registered"):
        sdl.applyUDF(df, "nope", "x", "y")
    from sparkdl_tpu.udf import unregisterUDF
    unregisterUDF("double_it")
    assert "double_it" not in sdl.listUDFs()


def test_register_named_model_image_udf():
    df, _ = image_df(n=2, parts=1)
    sdl.registerKerasImageUDF("rn18", "ResNet18", batchSize=2)
    out = sdl.applyUDF(df, "rn18", "image", "probs")
    rows = out.collect()
    assert len(rows[0].probs) == 1000


def test_logistic_regression_learns_separable():
    rng = np.random.default_rng(0)
    n = 200
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] + 2 * X[:, 1] > 0).astype(np.int32)
    df = sdl.DataFrame.fromPydict(
        {"features": X.tolist(), "label": y.tolist()}, numPartitions=3)
    lr = sdl.LogisticRegression(maxIter=200, stepSize=0.2,
                                probabilityCol="prob")
    model = lr.fit(df)
    out = model.transform(df)
    rows = out.collect()
    acc = np.mean([r.prediction == r.label for r in rows])
    assert acc > 0.95, acc
    assert abs(sum(rows[0].prob) - 1.0) < 1e-5
    assert model.numClasses == 2


def test_config1_pipeline_end_to_end(tmp_path):
    """BASELINE config 1 shape: featurizer + logreg in one Pipeline."""
    df, _ = image_df(n=8, parts=2, seed=3)
    pipe = sdl.Pipeline(stages=[
        sdl.DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="ResNet18", batchSize=4),
        sdl.LogisticRegression(maxIter=60, stepSize=0.3),
    ])
    pm = pipe.fit(df)
    rows = pm.transform(df).collect()
    assert all(r.prediction in (0, 1) for r in rows)
    # persistence of the whole fitted pipeline
    p = str(tmp_path / "pm")
    pm.save(p)
    loaded = sdl.load(p)
    rows2 = loaded.transform(df).collect()
    assert [r.prediction for r in rows] == [r.prediction for r in rows2]


def test_empty_partition_passthrough():
    # Regression: filter-emptied partitions must not crash transformers.
    df = sdl.DataFrame.fromPydict({"x": [[1.0], [2.0], [3.0], [4.0]]},
                                  numPartitions=2)
    emptied = df.filter(lambda r: r.x[0] <= 2.0)  # second partition empty
    t = sdl.XlaTransformer(inputCol="x", outputCol="y",
                           fn=lambda b: b * 3.0, batchSize=2)
    out = t.transform(emptied).collect()
    assert [r.y[0] for r in out] == [3.0, 6.0]

    idf, _ = image_df(n=4, parts=2)
    img_emptied = idf.filter(lambda r: r.image["origin"] in
                             ("mem://0", "mem://1"))
    ti = sdl.XlaImageTransformer(inputCol="image", outputCol="f",
                                 fn=lambda b: jnp.mean(b, axis=(1, 2, 3)),
                                 inputSize=(8, 8), batchSize=2)
    assert len(ti.transform(img_emptied).collect()) == 2

    with pytest.raises(ValueError, match="empty"):
        sdl.LogisticRegression().fit(
            sdl.DataFrame.fromPydict({"features": [], "label": []}))


def test_runner_cached_across_transform_calls():
    # Regression: repeated transform() must reuse one compiled runner.
    df, _ = image_df(n=2, parts=1)
    f = sdl.DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="ResNet18", batchSize=2)
    f.transform(df).collect()
    r1 = f._get_runner()
    f.transform(df).collect()
    assert f._get_runner() is r1


def test_xla_image_transformer_multi_device_sharded():
    """numDevices=-1 shards inference over the full mesh; results must be
    identical to the single-device path (SURVEY.md §2.4 row 2)."""
    df, _ = image_df(n=10, parts=2)
    fn = lambda b: jnp.mean(b, axis=(1, 2))
    single = sdl.XlaImageTransformer(inputCol="image", outputCol="f", fn=fn,
                                     inputSize=(8, 8), batchSize=4)
    multi = sdl.XlaImageTransformer(inputCol="image", outputCol="f", fn=fn,
                                    inputSize=(8, 8), batchSize=4,
                                    numDevices=-1)
    a = np.stack([r.f for r in single.transform(df).collect()])
    b = np.stack([r.f for r in multi.transform(df).collect()])
    np.testing.assert_allclose(a, b, atol=1e-6)
    with pytest.raises(ValueError, match="only"):
        sdl.XlaImageTransformer(inputCol="image", outputCol="f", fn=fn,
                                inputSize=(8, 8),
                                numDevices=99).transform(df)


def test_float_mode_image_column_keeps_float_feed():
    """CV_32FC3 image columns must NOT be quantized by the uint8 feed path
    (code-review r3): float pixels in [0,1] would all become 0."""
    import pyarrow as pa
    rng = np.random.default_rng(5)
    imgs = [rng.random((8, 8, 3), dtype=np.float32) for _ in range(3)]
    structs = [imageIO.imageArrayToStruct(im) for im in imgs]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}))
    t = sdl.XlaImageTransformer(inputCol="image", outputCol="feat",
                                fn=lambda b: b.mean(axis=(1, 2)),
                                inputSize=(8, 8), batchSize=4)
    got = np.asarray([r.feat for r in t.transform(df).collect()], np.float32)
    want = np.stack([im[:, :, ::-1].mean(axis=(0, 1)) for im in imgs])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_array_column_to_arrow_zero_width_and_types():
    from sparkdl_tpu.transformers.xla_image import arrayColumnToArrow
    import pyarrow as pa
    # zero-width rows: n empty lists, not a crash (code-review r3)
    arr = arrayColumnToArrow(np.zeros((4, 0), np.float32))
    assert arr.to_pylist() == [[], [], [], []]
    # int32-offset list type for normal sizes
    arr = arrayColumnToArrow(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert pa.types.is_list(arr.type)
    assert arr.to_pylist()[1] == [4.0, 5.0, 6.0, 7.0]


def test_featurizer_bfloat16_compute_close_to_f32():
    """computeDtype=bfloat16 (the MXU inference dtype) produces features
    within bf16 tolerance of the f32 path, on the same weights."""
    df, _ = image_df(n=3, parts=1)
    f32 = sdl.DeepImageFeaturizer(inputCol="image", outputCol="f",
                                  modelName="ResNet18", batchSize=4, seed=3)
    a = np.stack([np.asarray(r.f, np.float32)
                  for r in f32.transform(df).collect()])
    bf = sdl.DeepImageFeaturizer(inputCol="image", outputCol="f",
                                 modelName="ResNet18", batchSize=4, seed=3,
                                 computeDtype="bfloat16")
    b = np.stack([np.asarray(r.f, np.float32)
                  for r in bf.transform(df).collect()])
    assert b.dtype == np.float32  # features land f32 either way
    rel = np.abs(a - b) / (np.abs(a) + 1e-3)
    assert rel.mean() < 0.05, rel.mean()


def test_keras_image_parallel_loader_equivalence(tmp_path):
    """Thread-pool URI loading (loadImageBatch) produces the same batch as
    the serial path, in order."""
    from PIL import Image
    from sparkdl_tpu.transformers.keras_image import loadImageBatch
    rng = np.random.default_rng(0)
    uris = []
    for i in range(7):
        p = str(tmp_path / f"im{i}.png")
        Image.fromarray(rng.integers(0, 256, (9, 9, 3), np.uint8)).save(p)
        uris.append(p)
    from sparkdl_tpu.transformers.keras_image import defaultImageLoader
    loader = defaultImageLoader((9, 9))
    serial = np.stack([loader(u) for u in uris])
    pooled = loadImageBatch(loader, uris, workers=4)
    np.testing.assert_array_equal(pooled, serial)
