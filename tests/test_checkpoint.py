"""Verified checkpoints (ISSUE 4): manifests, corruption quarantine,
rollback-to-verified-step, idempotent wait/close, load_portable reporting.

All CPU-only with tiny synthetic states — the restore-fallback acceptance
runs in-process (the gang-level variant lives in test_chaos.py, slow)."""

import glob
import os

import numpy as np
import optax
import pytest

import jax
from sparkdl_tpu.runner import (CheckpointManager, TrainState, XlaRunner,
                                softmax_cross_entropy_loss)
from sparkdl_tpu.runner import chaos, events, metrics
from sparkdl_tpu.runner.chaos import corrupt_latest_checkpoint
from sparkdl_tpu.runner.checkpoint import (CheckpointCorruptionError,
                                           load_portable, save_portable)


def _state(value: float):
    return TrainState.create(
        None, {"w": np.full((4, 3), value, np.float32)}, optax.sgd(0.1))


def _two_step_dir(tmp_path):
    d = str(tmp_path / "ckpt")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _state(1.0), wait=True)
    m.save(2, _state(2.0), wait=True)
    return d, m


class TestVerifiedCheckpoints:
    def test_manifest_committed_per_step(self, tmp_path):
        d, m = _two_step_dir(tmp_path)
        names = sorted(os.path.basename(p)
                       for p in glob.glob(d + "/manifest_step_*.json"))
        assert names == ["manifest_step_1.json", "manifest_step_2.json"]
        assert m.verify_step(1) == (True, "ok")
        assert m.verify_step(2) == (True, "ok")
        m.close()

    def test_restore_falls_back_to_verified_step(self, tmp_path):
        """THE restore-fallback satellite: corrupt the latest step on
        disk; restore must quarantine it (dir renamed *.corrupt) and land
        on the previous verified step, recording the rollback."""
        metrics.run_stats.reset()
        d, m = _two_step_dir(tmp_path)
        assert corrupt_latest_checkpoint(d)  # damages step 2
        ok, reason = m.verify_step(2)
        assert not ok and reason
        restored = m.restore(_state(0.0))
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), 1.0)  # step 1's value
        corrupt_dirs = glob.glob(d + "/2.corrupt*")
        assert len(corrupt_dirs) == 1
        assert not os.path.exists(os.path.join(d, "2"))
        assert metrics.run_stats.checkpoint_rollbacks == 1
        assert "2 -> 1" in metrics.run_stats.last_rollback
        # the quarantined step's manifest is gone; step 1 restores again
        assert m.verify_step(1) == (True, "ok")
        m.close()
        metrics.run_stats.reset()

    def test_all_corrupt_raises_not_death_loops(self, tmp_path):
        d = str(tmp_path / "ckpt")
        m = CheckpointManager(d, async_save=False)
        m.save(1, _state(1.0), wait=True)
        corrupt_latest_checkpoint(d)
        with pytest.raises(CheckpointCorruptionError, match="no verified"):
            m.restore(_state(0.0))
        m.close()

    def test_explicit_corrupt_step_raises(self, tmp_path):
        """An explicitly pinned step never silently substitutes older
        state: corrupt it -> CheckpointCorruptionError."""
        d, m = _two_step_dir(tmp_path)
        corrupt_latest_checkpoint(d)
        with pytest.raises(CheckpointCorruptionError, match="step 2"):
            m.restore(_state(0.0), step=2)
        m.close()

    def test_legacy_dir_without_manifests_still_restores(self, tmp_path):
        d, m = _two_step_dir(tmp_path)
        for p in glob.glob(d + "/manifest_step_*.json"):
            os.unlink(p)
        restored = m.restore(_state(0.0))
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
        m.close()

    def test_verify_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDL_CHECKPOINT_VERIFY", "0")
        d = str(tmp_path / "ckpt")
        m = CheckpointManager(d, async_save=False)
        m.save(1, _state(1.0), wait=True)
        assert glob.glob(d + "/manifest_step_*.json") == []
        m.close()

    def test_wait_close_idempotent_and_safe_before_first_save(self, tmp_path):
        m = CheckpointManager(str(tmp_path / "ckpt"))
        m.wait()
        m.wait()
        m.close()
        m.close()  # double close: no-op
        m2 = CheckpointManager(str(tmp_path / "ckpt2"))
        m2.save(1, _state(1.0), wait=False)
        m2.wait()  # finalizes the async save's manifest
        assert m2.verify_step(1) == (True, "ok")
        m2.close()
        m2.wait()  # after close: no-op, no raise

    def test_fit_error_path_closes_manager_once(self, tmp_path):
        """ISSUE 4 satellite: a failing fit closes its CheckpointManager
        (finalizing the in-flight save + manifest) and drops the cached
        instance so the context property can re-open."""
        runner = XlaRunner(np=8, checkpoint_dir=str(tmp_path / "ckpt"))
        ctx = runner.make_context()
        rng = np.random.RandomState(0)

        def data():
            while True:
                yield {"image": rng.randn(8, 4).astype(np.float32),
                       "label": rng.randint(0, 3, (8,))}

        def boom():
            it = data()
            for i, b in enumerate(it):
                if i == 3:
                    raise RuntimeError("UNAVAILABLE: injected")
                yield b

        with ctx.mesh:
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                ctx.fit(loss_fn=softmax_cross_entropy_loss(),
                        params={"w": rng.randn(4, 3).astype(np.float32)},
                        tx=optax.sgd(0.1),
                        apply_fn=lambda p, x: x @ p["w"], data=boom(),
                        num_steps=6, checkpoint_every=2, log_every=100)
        assert ctx._ckpt is None  # closed exactly once and dropped
        # the save that was in flight at failure time is fully committed
        m = CheckpointManager(str(tmp_path / "ckpt"))
        assert m.latest_step() == 2
        assert m.verify_step(2) == (True, "ok")
        m.close()

    def test_fit_resumes_past_corrupt_checkpoint(self, tmp_path):
        """In-process acceptance: corrupt the latest checkpoint, rerun
        fit(resume=True) — it rolls back to the previous verified step
        and completes instead of death-looping."""
        metrics.run_stats.reset()
        ckpt = str(tmp_path / "ckpt")
        rng = np.random.RandomState(1)
        params = {"w": rng.randn(4, 3).astype(np.float32)}

        def data(n):
            r = np.random.RandomState(2)
            for _ in range(n):
                yield {"image": r.randn(8, 4).astype(np.float32),
                       "label": r.randint(0, 3, (8,))}

        kw = dict(loss_fn=softmax_cross_entropy_loss(), params=params,
                  tx=optax.sgd(0.1), apply_fn=lambda p, x: x @ p["w"],
                  checkpoint_every=2, log_every=100)
        r1 = XlaRunner(np=8, checkpoint_dir=ckpt).run(
            lambda ctx: ctx.fit(data=data(12), num_steps=4, **kw))
        assert int(r1["state"].step) == 4
        assert corrupt_latest_checkpoint(ckpt)
        r2 = XlaRunner(np=8, checkpoint_dir=ckpt).run(
            lambda ctx: ctx.fit(data=data(12), num_steps=6, **kw))
        assert int(r2["state"].step) == 6
        # resumed from step 2, not 4: ran 4 steps, rolled back once
        assert r2["meter"].steps == 4
        assert metrics.run_stats.checkpoint_rollbacks == 1
        assert glob.glob(ckpt + "/4.corrupt*")
        metrics.run_stats.reset()


class TestLoadPortable:
    def test_reports_all_mismatches_in_one_error(self, tmp_path):
        path = str(tmp_path / "w.safetensors")
        save_portable({"a": {"w": np.ones((2, 2), np.float32)},
                       "extra": np.ones((1,), np.float32),
                       "b": np.ones((3,), np.float32)}, path)
        template = {"a": {"w": np.zeros((2, 3), np.float32)},  # mismatch
                    "b": np.zeros((3,), np.float32),           # ok
                    "missing1": np.zeros((1,), np.float32),
                    "missing2": np.zeros((1,), np.float32)}
        with pytest.raises(ValueError) as ei:
            load_portable(template, path)
        msg = str(ei.value)
        # ALL problems in ONE message, with param-tree paths
        assert "missing1" in msg and "missing2" in msg
        assert "extra" in msg
        assert "a/w" in msg and "(2, 2)" in msg and "(2, 3)" in msg

    def test_clean_roundtrip_still_works(self, tmp_path):
        path = str(tmp_path / "w.safetensors")
        params = {"a": {"w": np.arange(4, dtype=np.float32).reshape(2, 2)}}
        save_portable(params, path)
        out = load_portable(
            {"a": {"w": np.zeros((2, 2), np.float32)}}, path)
        np.testing.assert_array_equal(np.asarray(out["a"]["w"]),
                                      params["a"]["w"])


class TestReviewRegressions:
    """Pins for the PR-4 review findings."""

    def test_legacy_steps_survive_manifest_upgrade(self, tmp_path,
                                                   monkeypatch):
        """Steps saved pre-manifest are valid restore points: when a
        newer manifested step is corrupt, restore falls back to the
        legacy step UNVERIFIED instead of quarantining it."""
        d = str(tmp_path / "ckpt")
        monkeypatch.setenv("SPARKDL_CHECKPOINT_VERIFY", "0")
        m = CheckpointManager(d, async_save=False)
        m.save(1, _state(1.0), wait=True)  # legacy: no manifest
        m.close()
        monkeypatch.delenv("SPARKDL_CHECKPOINT_VERIFY")
        m2 = CheckpointManager(d, async_save=False)
        m2.save(2, _state(2.0), wait=True)  # manifested
        assert corrupt_latest_checkpoint(d)
        restored = m2.restore(_state(0.0))
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 1.0)
        assert os.path.isdir(os.path.join(d, "1"))  # NOT quarantined
        assert glob.glob(d + "/2.corrupt*")
        m2.close()

    def test_uncommitted_partial_save_is_quarantined(self, tmp_path):
        """A step NEWER than the newest manifest (killed between the save
        landing and its manifest commit) is the partial-save case:
        quarantined, fallback to the verified step. (A dir orbax never
        committed at all is already excluded by orbax's own
        latest_step.)"""
        d = str(tmp_path / "ckpt")
        m = CheckpointManager(d, async_save=False)
        m.save(1, _state(1.0), wait=True)
        m.save(2, _state(2.0), wait=True)
        os.unlink(os.path.join(d, "manifest_step_2.json"))  # died pre-commit
        restored = m.restore(_state(0.0))
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 1.0)
        assert glob.glob(d + "/2.corrupt*")
        m.close()

    def test_restore_finalizes_inflight_async_save(self, tmp_path):
        """restore() right after save(wait=False) must land + certify the
        pending save, not quarantine the step orbax is still writing."""
        d = str(tmp_path / "ckpt")
        m = CheckpointManager(d)  # async
        m.save(1, _state(1.0), wait=True)
        m.save(2, _state(2.0), wait=False)
        restored = m.restore(_state(0.0))
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
        assert not glob.glob(d + "/*.corrupt*")
        assert m.verify_step(2) == (True, "ok")
        m.close()


class TestElasticReshard:
    """ISSUE 16: manifests fingerprint the save-time topology; restoring
    into a different mesh either refuses with a clear topology error
    (default) or — under SPARKDL_ELASTIC=1 — re-lays-out every leaf over
    the new mesh through divisible_rules, bit-identical. conftest forces
    8 virtual CPU devices, so meshes of 4/2/1 model the world sizes a
    shrinking gang passes through."""

    @staticmethod
    def _mesh(n, axis="data"):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:n]), (axis,))

    @staticmethod
    def _tree(value=None, seed=0):
        """8x4 kernel (divides at 4/2/1), 4-dim bias (1-D, replicated),
        6x4 table (6 splits at 2/1 but NOT 4 — exercises the
        divisible-fallback on both the save and restore layouts)."""
        rng = np.random.RandomState(seed)

        def leaf(*shape):
            if value is not None:
                return np.full(shape, value, np.float32)
            return rng.randn(*shape).astype(np.float32)

        return {"dense": {"kernel": leaf(8, 4), "bias": leaf(4)},
                "table": {"kernel": leaf(6, 4)}}

    def _save_fsdp(self, d, n_dev, step=3):
        from sparkdl_tpu.parallel.sharding import (divisible_rules,
                                                   fsdp_rules, shard_params)
        mesh = self._mesh(n_dev)
        rules = fsdp_rules(mesh=mesh)
        state = TrainState.create(None, self._tree(), optax.sgd(0.1))
        sharded = shard_params(state, mesh, divisible_rules(rules, mesh))
        m = CheckpointManager(d, async_save=False)
        m.save(step, sharded, wait=True)
        m.close()
        return jax.tree_util.tree_map(np.asarray, sharded.params)

    def test_fsdp_shrink_roundtrip_bit_identical(self, tmp_path,
                                                 monkeypatch):
        """Save at world 4, restore at 2 and at 1: every param leaf equals
        the original bit-for-bit and lives on the NEW mesh."""
        from sparkdl_tpu.parallel.sharding import fsdp_rules
        d = str(tmp_path / "ckpt")
        originals = self._save_fsdp(d, 4)
        monkeypatch.setenv("SPARKDL_ELASTIC", "1")
        for n in (2, 1):
            mesh = self._mesh(n)
            template = TrainState.create(None, self._tree(value=0.0),
                                         optax.sgd(0.1))
            m = CheckpointManager(d)
            restored = m.restore(template, mesh=mesh,
                                 rules=fsdp_rules(mesh=mesh))
            m.close()
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                           b),
                restored.params, originals)
            k = restored.params["dense"]["kernel"]
            assert dict(k.sharding.mesh.shape) == {"data": n}
            assert int(restored.step) == 0  # template's fresh step layout

    def test_fsdp_grow_roundtrip_bit_identical(self, tmp_path, monkeypatch):
        """The grow-back direction: saved by the SHRUNKEN gang (world 2),
        restored by the recovered one (world 4)."""
        from sparkdl_tpu.parallel.sharding import fsdp_rules
        d = str(tmp_path / "ckpt")
        originals = self._save_fsdp(d, 2)
        monkeypatch.setenv("SPARKDL_ELASTIC", "1")
        mesh4 = self._mesh(4)
        template = TrainState.create(None, self._tree(value=0.0),
                                     optax.sgd(0.1))
        m = CheckpointManager(d)
        restored = m.restore(template, mesh=mesh4,
                             rules=fsdp_rules(mesh=mesh4))
        m.close()
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            restored.params, originals)
        assert dict(restored.params["dense"]["kernel"]
                    .sharding.mesh.shape) == {"data": 4}

    def test_serving_tp_layout_reshard_roundtrip(self, tmp_path,
                                                 monkeypatch):
        """The serving rule set reshards too: a tp=4 engine checkpoint
        restores onto a tp=2 mesh with identical weights."""
        from sparkdl_tpu.parallel.sharding import (divisible_rules,
                                                   serving_tp_layout,
                                                   shard_params)
        rng = np.random.RandomState(7)
        params = {p: {"kernel": rng.randn(8, 8).astype(np.float32)}
                  for p in ("q_proj", "o_proj", "up_proj", "down_proj")}
        mesh4 = self._mesh(4, axis="tp")
        layout4 = serving_tp_layout(4)
        sharded = shard_params(params, mesh4,
                               divisible_rules(layout4.rules, mesh4))
        d = str(tmp_path / "ckpt")
        m = CheckpointManager(d, async_save=False)
        state = TrainState.create(None, sharded, optax.sgd(0.1))
        m.save(1, state, wait=True)
        m.close()
        originals = jax.tree_util.tree_map(np.asarray, sharded)

        monkeypatch.setenv("SPARKDL_ELASTIC", "1")
        mesh2 = self._mesh(2, axis="tp")
        template = TrainState.create(
            None, jax.tree_util.tree_map(np.zeros_like, originals),
            optax.sgd(0.1))
        m2 = CheckpointManager(d)
        restored = m2.restore(template, mesh=mesh2,
                              rules=serving_tp_layout(2).rules)
        m2.close()
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            restored.params, originals)
        q = restored.params["q_proj"]["kernel"]
        assert dict(q.sharding.mesh.shape) == {"tp": 2}

    def test_mismatch_without_elastic_raises_topology_error(
            self, tmp_path, monkeypatch):
        """The default (SPARKDL_ELASTIC unset) must fail loudly at the
        TOPOLOGY layer — naming both layouts and the env knob — not leak
        a device_put shape error from orbax."""
        from sparkdl_tpu.parallel.sharding import fsdp_rules
        from sparkdl_tpu.runner.checkpoint import CheckpointTopologyError
        d = str(tmp_path / "ckpt")
        self._save_fsdp(d, 4)
        monkeypatch.delenv("SPARKDL_ELASTIC", raising=False)
        mesh2 = self._mesh(2)
        template = TrainState.create(None, self._tree(value=0.0),
                                     optax.sgd(0.1))
        m = CheckpointManager(d)
        with pytest.raises(CheckpointTopologyError) as ei:
            m.restore(template, step=3, mesh=mesh2,
                      rules=fsdp_rules(mesh=mesh2))
        m.close()
        msg = str(ei.value)
        assert "topology mismatch" in msg
        assert "'data': 4" in msg and "'data': 2" in msg
        assert "SPARKDL_ELASTIC" in msg

    def test_same_topology_restore_unaffected(self, tmp_path, monkeypatch):
        """No mismatch -> the pre-ISSUE-16 path exactly: no elastic env
        needed, no reshard event, works with mesh passed or not."""
        from sparkdl_tpu.parallel.sharding import fsdp_rules
        d = str(tmp_path / "ckpt")
        originals = self._save_fsdp(d, 4)
        monkeypatch.delenv("SPARKDL_ELASTIC", raising=False)
        mesh4 = self._mesh(4)
        template = TrainState.create(None, self._tree(value=0.0),
                                     optax.sgd(0.1))
        m = CheckpointManager(d)
        restored = m.restore(template, mesh=mesh4,
                             rules=fsdp_rules(mesh=mesh4))
        m.close()
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            restored.params, originals)
