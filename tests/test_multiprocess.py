"""Multi-process XlaRunner proof (SURVEY.md §2.5/§3.5 hard-part #1;
round-1 verdict item 2) + gang-supervision tests (ISSUE 1 tentpole).

Spawns 2 REAL OS processes via runner.launcher (the mpirun role), each with
one local CPU device; jax.distributed + gloo provide rendezvous and the
cross-process collective transport. The worker asserts gradient-allreduce
equivalence against a single-device reference over the global batch —
the same equivalence bar the in-process tests use.

The supervision tests use tiny jax-free scripts (fast, tier-1) plus one
slow real-training gang where a chaos plan SIGKILLs a rank mid-run.
"""

import os
import sys
import time

import pytest

from sparkdl_tpu.runner import launcher
from sparkdl_tpu.runner.chaos import Fault, FaultPlan
from sparkdl_tpu.runner.launcher import GangFailure, supervise

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")
_CHAOS_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "chaos_mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_train_and_collectives(tmp_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # each worker gets exactly ONE local cpu device (the parent test
        # env forces 8 — undo that so global mesh = 2 processes x 1)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    results = launcher.launch(_WORKER, np=2, args=[str(tmp_path)], env=env,
                              timeout_s=420.0, capture=True)
    assert (tmp_path / "rank0.ok").exists(), results[0].stderr[-2000:]
    assert (tmp_path / "rank1.ok").exists(), results[1].stderr[-2000:]


def test_launcher_propagates_failures(tmp_path):
    bad = tmp_path / "boom.py"
    bad.write_text("import sys; sys.exit(3)\n")
    with pytest.raises(RuntimeError, match="rank"):
        launcher.launch(str(bad), np=2, timeout_s=60.0, capture=True)


def test_launcher_rejects_bad_np():
    with pytest.raises(ValueError):
        launcher.launch("x.py", np=0)
    with pytest.raises(ValueError):
        supervise("x.py", np=0)


class TestGangSupervision:
    """Poll-loop, watchdog, and restart-budget behavior via tiny jax-free
    worker scripts — fast enough for tier-1."""

    def test_dead_rank_detected_within_poll_not_timeout(self, tmp_path):
        """One rank dies while its peer 'hangs on a collective' (sleeps):
        the old sequential wait burned the full timeout_s; the poll loop
        must detect, kill the gang, and raise within seconds."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '1':\n"
            "    print('boom-rank-1', file=sys.stderr)\n"
            "    sys.exit(3)\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25)
        wall = time.monotonic() - t0
        assert wall < 30, f"detection took {wall:.1f}s (poll loop broken?)"
        assert "rank(s) [1]" in str(ei.value)
        assert "boom-rank-1" in str(ei.value)  # salvaged stderr
        assert ei.value.kind == "retryable"

    def test_timeout_salvages_which_rank_stalled(self, tmp_path):
        """On timeout the error must name the rank that stopped making
        progress and carry the completed ranks' output (the postmortem
        the old communicate()-then-raise path threw away)."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0':\n"
            "    print('rank0-finished-cleanly', file=sys.stderr)\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n")
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=4.0, capture=True,
                            poll_s=0.25)
        msg = str(ei.value)
        assert "rank(s) [1] still running" in msg
        assert "rank(s) [0] had exited" in msg
        assert "rank0-finished-cleanly" in msg
        assert ei.value.hung

    def test_watchdog_detects_stale_heartbeat(self, tmp_path):
        """A rank that beats once then stalls must be caught by the
        heartbeat watchdog long before timeout_s."""
        hb = tmp_path / "hb"
        hb.mkdir()
        script = tmp_path / "w.py"
        script.write_text(
            "import os, time\n"
            "d = os.environ['SPARKDL_HEARTBEAT_DIR']\n"
            "r = os.environ['SPARKDL_PROCESS_ID']\n"
            "open(os.path.join(d, 'rank%s.hb' % r), 'w').write('7')\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25,
                            heartbeat_dir=str(hb), watchdog_s=1.5)
        wall = time.monotonic() - t0
        assert wall < 30, f"watchdog took {wall:.1f}s"
        assert ei.value.hung and ei.value.kind == "retryable"
        assert "heartbeat watchdog" in str(ei.value)
        assert "step 7" in str(ei.value)  # where progress stopped

    def test_supervise_restarts_retryable_and_succeeds(self, tmp_path):
        """First attempt dies with an UNAVAILABLE-shaped error; supervise
        must classify retryable, relaunch, and report exactly 1 restart."""
        script = tmp_path / "w.py"
        # Only rank 0 fails (and only once, via the marker): if both ranks
        # raced to fail, the gang kill could reach the slower rank before
        # its marker write and cost a second, nondeterministic restart.
        script.write_text(
            "import os, sys\n"
            "m = sys.argv[1]\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0' "
            "and not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    print('UNAVAILABLE: injected backend flake',"
            " file=sys.stderr)\n"
            "    sys.exit(1)\n")
        res = supervise(str(script), np=2, args=[str(tmp_path / "m")],
                        timeout_s=60.0, max_restarts=2, backoff_s=0.05,
                        poll_s=0.25)
        assert res.restarts == 1 and res.attempts == 2
        assert res.failure_kinds == ["retryable"]
        assert all(r.returncode == 0 for r in res.results)

    def test_supervise_fatal_does_not_retry(self, tmp_path):
        """A ValueError-shaped death must not burn the restart budget."""
        script = tmp_path / "w.py"
        script.write_text(
            "import sys\n"
            "with open(sys.argv[1], 'a') as f: f.write('attempt\\n')\n"
            "raise ValueError('user bug')\n")
        count = tmp_path / "count"
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=2, args=[str(count)], timeout_s=60.0,
                      max_restarts=3, backoff_s=0.05, poll_s=0.25)
        assert ei.value.kind == "fatal"
        # One attempt only (<= 2 writes: the gang kill may reach the
        # slower rank before its append) — a retry would write 3+.
        assert 1 <= count.read_text().count("attempt") <= 2

    def test_supervise_budget_exhaustion(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text("import sys\n"
                          "print('UNAVAILABLE: forever', file=sys.stderr)\n"
                          "sys.exit(1)\n")
        with pytest.raises(GangFailure, match="giving up after 1"):
            supervise(str(script), np=2, timeout_s=60.0, max_restarts=1,
                      backoff_s=0.05, poll_s=0.25)


# Jax-free poison worker: dies with a batch-attributed failure (postmortem
# into SPARKDL_EVENT_DIR, the evidence the timeline correlates on) until
# its poison batch lands on SPARKDL_SKIP_BATCHES, then exits 0. `mode`
# picks the stderr/classification shape: retryable (UNAVAILABLE) or fatal
# (TrainingDivergedError, the NaN-poison signature). `pick` chooses the
# poison batch; "next_unskipped" models a systematically bad dataset
# (a NEW poison appears whenever one is skipped) for the circuit breaker.
_POISON_WORKER = """
import json, os, sys, time
skip = json.loads(os.environ.get("SPARKDL_SKIP_BATCHES", "[]"))
mode = {mode!r}
bi = {pick}
if bi is None:
    sys.exit(0)
d = os.environ["SPARKDL_EVENT_DIR"]
err = ({{"type": "TrainingDivergedError",
        "message": "training diverged: non-finite loss (nan) at step %d" % bi}}
       if mode == "fatal" else
       {{"type": "InjectedPreemption", "message": "UNAVAILABLE: poison"}})
pm = {{"t": time.time(), "rank": 0, "site": "fit", "step": bi,
      "batch_index": bi, "error": err}}
tmp = os.path.join(d, "postmortem_rank0.json.tmp")
open(tmp, "w").write(json.dumps(pm))
os.replace(tmp, os.path.join(d, "postmortem_rank0.json"))
print(err["type"] + ": " + err["message"], file=sys.stderr)
sys.exit(1)
"""


def _poison_script(tmp_path, mode="retryable",
                   pick="8 if 8 not in skip else None"):
    script = tmp_path / "poison.py"
    script.write_text(_POISON_WORKER.format(mode=mode, pick=pick))
    return str(script)


class TestPoisonBatchQuarantine:
    """ISSUE 5 tentpole, supervisor half: consecutive failures at one
    (step, batch_index) quarantine the batch instead of burning the
    restart budget; without the skip-list the same job death-loops (the
    pre-ISSUE-5 counterfactual); SPARKDL_MAX_SKIPPED_BATCHES is the
    circuit breaker. Jax-free workers — fast enough for tier-1; the
    real-training end-to-end is scripts/train_resume_smoke.py (slow)."""

    def test_retryable_poison_quarantined_after_two_failures(self, tmp_path):
        from sparkdl_tpu.runner import metrics
        metrics.run_stats.reset()
        res = supervise(_poison_script(tmp_path), np=1, timeout_s=30.0,
                        max_restarts=1, backoff_s=0.05, poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["retryable", "quarantined"]
        assert res.restarts == 2  # one budgeted + one free quarantine
        names = [d.get("name") for d in res.degradations]
        assert "train_batch_quarantined" in names
        q = next(d for d in res.degradations
                 if d.get("name") == "train_batch_quarantined")
        assert q["batch_index"] == 8 and q["skip_list"] == [8]
        assert metrics.run_stats.train_batches_quarantined == 1
        metrics.run_stats.reset()

    def test_fatal_poison_gets_probe_restart_then_quarantine(self, tmp_path):
        """A batch-attributed FATAL failure (TrainingDivergedError from a
        NaN record) must not give up outright: one budgeted probe restart
        tests determinism, recurrence quarantines."""
        res = supervise(_poison_script(tmp_path, mode="fatal"), np=1,
                        timeout_s=30.0, max_restarts=1, backoff_s=0.05,
                        poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["fatal", "quarantined"]

    def test_fatal_probe_not_blocked_by_earlier_unrelated_signature(
            self, tmp_path):
        """Review regression: a batch-attributed FATAL arriving after an
        unrelated batch-attributed retryable failure must still get its
        probe restart (the old gate required prev_sig to be None, so the
        genuine poison gave up unprobed)."""
        script = tmp_path / "w.py"
        script.write_text("""
import json, os, sys, time
marker, skip = sys.argv[1], json.loads(
    os.environ.get("SPARKDL_SKIP_BATCHES", "[]"))
if not os.path.exists(marker):
    # attempt 1: transient draw flake at batch 3, retryable-shaped
    open(marker, "w").write("x")
    bi, err = 3, {"type": "InjectedPreemption",
                  "message": "UNAVAILABLE: transient flake"}
elif 8 not in skip:
    # attempts 2+: deterministic NaN poison at batch 8, fatal-shaped
    bi, err = 8, {"type": "TrainingDivergedError",
                  "message": "training diverged: non-finite loss (nan)"}
else:
    sys.exit(0)
d = os.environ["SPARKDL_EVENT_DIR"]
pm = {"t": time.time(), "rank": 0, "site": "fit", "step": bi,
      "batch_index": bi, "error": err}
tmp = os.path.join(d, "postmortem_rank0.json.tmp")
open(tmp, "w").write(json.dumps(pm))
os.replace(tmp, os.path.join(d, "postmortem_rank0.json"))
print(err["type"] + ": " + err["message"], file=sys.stderr)
sys.exit(1)
""")
        res = supervise(str(script), np=1, args=[str(tmp_path / "m")],
                        timeout_s=30.0, max_restarts=3, backoff_s=0.05,
                        poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["retryable", "fatal", "quarantined"]

    def test_counterfactual_death_loop_without_quarantine(self, tmp_path):
        """The pre-ISSUE-5 behavior, pinned: the identical poison job with
        quarantine_batches=False replays into the same batch every
        attempt and exhausts the restart budget."""
        script = _poison_script(tmp_path, pick="8")  # never recovers
        with pytest.raises(GangFailure, match="giving up after 2"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=2,
                      backoff_s=0.05, poll_s=0.2,
                      quarantine_batches=False)

    def test_batchless_fatal_still_fails_fast(self, tmp_path):
        """A fatal failure with NO batch attribution keeps today's
        immediate give-up — the probe restart is only for failures the
        quarantine could act on."""
        script = tmp_path / "w.py"
        script.write_text(
            "import sys\nraise ValueError('user bug, no batch')\n")
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=1, timeout_s=30.0, max_restarts=3,
                      backoff_s=0.05, poll_s=0.2)
        assert ei.value.kind == "fatal"
        assert "giving up after 0 restart(s)" in str(ei.value)

    def test_unskippable_poison_fails_fast_not_requarantine_loop(
            self, tmp_path):
        """Review regression: a poison the dataset CANNOT skip (draw-time
        raise in a non-seekable source — the worker here keeps dying at
        batch 8 even after it is skip-listed) must not alternate
        quarantine/restart forever: one quarantine attempt, then the
        normal budget policy, no duplicate skip-list entries."""
        script = _poison_script(tmp_path, pick="8")  # ignores skip-list
        with pytest.raises(GangFailure,
                           match=r"giving up after 2 restart\(s\)"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=2,
                      backoff_s=0.05, poll_s=0.2)

    def test_max_skipped_batches_circuit_breaker(self, tmp_path):
        """A dataset that presents a NEW poison batch whenever one is
        skipped is systematically bad: past the cap the supervisor raises
        fatal PoisonDataError instead of eating the dataset."""
        from sparkdl_tpu.runner.failures import PoisonDataError
        script = _poison_script(tmp_path, pick="len(skip)")
        with pytest.raises(PoisonDataError, match="circuit breaker"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=8,
                      backoff_s=0.05, poll_s=0.2, max_skipped_batches=2)


@pytest.mark.slow
@pytest.mark.chaos
def test_supervise_sigkilled_rank_relaunches_to_completion(tmp_path):
    """The acceptance gang test: a chaos plan SIGKILLs rank 1 at step 2 of
    a real 2-process training run. The supervisor must detect the dead
    rank within a poll interval (not the full timeout_s), kill the gang,
    classify retryable, and relaunch to completion within the budget —
    the plan's state_dir guarantees the kill fires only once."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    plan = FaultPlan([Fault("step_start", "sigkill", at_step=2, rank=1)])
    t0 = time.monotonic()
    res = supervise(_CHAOS_WORKER, np=2, args=[str(tmp_path)], env=env,
                    timeout_s=600.0, max_restarts=2, backoff_s=0.1,
                    poll_s=0.5, plan=plan)
    wall = time.monotonic() - t0
    assert res.restarts == 1, res.failure_kinds
    assert res.failure_kinds == ["retryable"]
    assert (tmp_path / "rank0.ok").exists()
    assert (tmp_path / "rank1.ok").exists()
    # Prompt detection: total wall includes 2 full jax startups but must
    # sit far below even ONE timeout_s — the old sequential wait would
    # have burned 600s before noticing the dead rank.
    assert wall < 300, f"supervise took {wall:.0f}s — timeout-driven?"


# ISSUE 16: elastic gang supervision. Pure-stdlib workers (no sparkdl
# import in the child — spawn cost stays ~100ms) that fail selectively by
# (SPARKDL_NUM_PROCESSES, SPARKDL_PROCESS_ID) and marker files, so each
# test scripts an exact sequence of gang attempts. The real-training
# version (checkpoint resharding + ledger audit) is
# scripts/elastic_smoke.py below.
_DEAD_SLOT_WORKER = """
import os, sys
w, r = os.environ["SPARKDL_NUM_PROCESSES"], os.environ["SPARKDL_PROCESS_ID"]
recovered = sys.argv[1] if len(sys.argv) > 1 else ""
if w == "3" and r == "2" and not (recovered and os.path.exists(recovered)):
    print("UNAVAILABLE: slot lost", file=sys.stderr)
    sys.exit(1)
"""


class TestElasticSupervision:
    """ISSUE 16 tentpole, policy half: a PERMANENTLY dead rank (same rank,
    same world size, two consecutive attempts) shrinks the gang instead of
    burning the restart budget; recovered capacity grows it back via a
    probe on the next budgeted restart; SPARKDL_ELASTIC_MIN_NP floors the
    shrink; without SPARKDL_ELASTIC=1 the same job death-loops."""

    def _dead_slot(self, tmp_path, recovered=""):
        script = tmp_path / "w.py"
        script.write_text(_DEAD_SLOT_WORKER)
        return str(script), ([recovered] if recovered else [])

    def test_permanent_rank_death_shrinks_without_burning_budget(
            self, tmp_path):
        """rank 2 of 3 dies in two consecutive attempts -> free shrink to
        np=2 and completion. max_restarts=1 is the budget proof: a
        budget-consuming resize could never reach the third attempt."""
        from sparkdl_tpu.runner import metrics
        metrics.run_stats.reset()
        script, args = self._dead_slot(tmp_path)
        res = supervise(script, np=3, args=args, timeout_s=30.0,
                        max_restarts=1, backoff_s=0.05, poll_s=0.2,
                        env={"SPARKDL_ELASTIC": "1"})  # env path, not kwarg
        assert res.failure_kinds == ["retryable", "resized"]
        assert res.resizes == 1 and res.final_np == 2
        assert res.restarts == 2  # one budgeted + one free resize
        ev = next(d for d in res.degradations
                  if d.get("name") == "gang_resized")
        assert (ev["from_np"], ev["to_np"], ev["dead_rank"]) == (3, 2, 2)
        assert metrics.run_stats.resizes == 1
        assert "np 3 -> 2" in metrics.run_stats.last_resize
        metrics.run_stats.reset()

    def test_transient_failure_does_not_resize(self, tmp_path):
        """One rank dying ONCE is a normal budgeted restart — correlation
        requires the same (world, rank) twice in a row."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys\n"
            "m = sys.argv[1]\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '1' "
            "and not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    print('UNAVAILABLE: flake', file=sys.stderr)\n"
            "    sys.exit(1)\n")
        res = supervise(script, np=3, args=[str(tmp_path / "m")],
                        timeout_s=30.0, max_restarts=2, backoff_s=0.05,
                        poll_s=0.2, elastic=True)
        assert res.failure_kinds == ["retryable"]
        assert res.resizes == 0 and res.final_np == 3

    def test_min_np_floor_gives_up_with_clear_error(self, tmp_path):
        """A permanent death whose shrink would pass the floor must give
        up and say WHY (floor, env knob), not device-loop."""
        script, args = self._dead_slot(tmp_path)
        with pytest.raises(GangFailure) as ei:
            supervise(script, np=3, args=args, timeout_s=30.0,
                      max_restarts=1, backoff_s=0.05, poll_s=0.2,
                      elastic=True, min_np=3)
        msg = str(ei.value)
        assert "elastic floor" in msg and "SPARKDL_ELASTIC_MIN_NP" in msg
        assert "rank 2 of 3 is permanently dead" in msg

    def test_recovered_capacity_grows_back_via_probe(self, tmp_path):
        """After a shrink, the next BUDGETED restart probes the original
        world size; with the slot recovered the gang finishes grown."""
        recovered, flake = tmp_path / "recovered", tmp_path / "flake"
        script = tmp_path / "w.py"
        script.write_text(_DEAD_SLOT_WORKER + f"""
if w == "2" and r == "0" and not os.path.exists({str(flake)!r}):
    open({str(flake)!r}, "w").write("x")
    open({str(recovered)!r}, "w").write("x")  # slot comes back
    print("UNAVAILABLE: transient flake", file=sys.stderr)
    sys.exit(1)
""")
        res = supervise(str(script), np=3, args=[str(recovered)],
                        timeout_s=30.0, max_restarts=3, backoff_s=0.05,
                        poll_s=0.2, elastic=True)
        # shrink 3->2 (free), flake at 2 (budgeted) triggers grow probe
        # 2->3, probe succeeds: finishes at the ORIGINAL world size.
        assert res.failure_kinds == ["retryable", "resized", "retryable"]
        assert res.resizes == 2 and res.final_np == 3
        reasons = [d.get("reason") for d in res.degradations
                   if d.get("name") == "gang_resized"]
        assert reasons == ["rank_dead", "grow_probe"]

    def test_failed_probe_reverts_free_and_finishes_shrunk(self, tmp_path):
        """A grow probe into a STILL-dead slot must revert to the shrunken
        size without consuming budget — probing is bounded, not a second
        death loop."""
        flake = tmp_path / "flake"
        script = tmp_path / "w.py"
        script.write_text(_DEAD_SLOT_WORKER + f"""
if w == "2" and r == "0" and not os.path.exists({str(flake)!r}):
    open({str(flake)!r}, "w").write("x")
    print("UNAVAILABLE: transient flake", file=sys.stderr)
    sys.exit(1)
""")
        res = supervise(str(script), np=3, timeout_s=30.0,
                        max_restarts=2, backoff_s=0.05, poll_s=0.2,
                        elastic=True)
        assert res.failure_kinds == ["retryable", "resized", "retryable",
                                     "probe_failed"]
        assert res.final_np == 2
        assert res.resizes == 3  # shrink, grow probe, free revert
        assert res.restarts == 4  # only 2 of which touched the budget

    def test_elastic_off_death_loops(self, tmp_path):
        """The pre-ISSUE-16 counterfactual, pinned: same permanently dead
        slot, no SPARKDL_ELASTIC -> the budget burns down and the gang
        dies at full size."""
        script, args = self._dead_slot(tmp_path)
        with pytest.raises(GangFailure, match="giving up after 1"):
            supervise(script, np=3, args=args, timeout_s=30.0,
                      max_restarts=1, backoff_s=0.05, poll_s=0.2)


@pytest.mark.slow
@pytest.mark.chaos
def test_elastic_smoke_script():
    """scripts/elastic_smoke.py end-to-end (ISSUE 16 acceptance): a 4-rank
    CPU training gang loses rank 2 PERMANENTLY (decimate) at step 5,
    shrinks to 3 without consuming budget, reshards the 4-rank checkpoint
    at the 3-rank mesh, and finishes with the batch ledger proving
    exactly-once consumption across the resize; the identical job with
    SPARKDL_ELASTIC=0 death-loops through its restart budget."""
    import json
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "elastic_smoke.py")],
        capture_output=True, text=True, timeout=580,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, \
        f"stdout={proc.stdout[-2000:]}\nstderr={proc.stderr[-2000:]}"
    rec = json.loads([ln for ln in proc.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["ok"] is True
    assert rec["job_completed_at_ws3"] is True
    assert rec["resize_was_free"] is True
    assert rec["ledger_exactly_once_across_resize"] is True
    assert rec["ledger_records_resize"] is True
    assert rec["counterfactual_death_loops"] is True
