"""Multi-process XlaRunner proof (SURVEY.md §2.5/§3.5 hard-part #1;
round-1 verdict item 2).

Spawns 2 REAL OS processes via runner.launcher (the mpirun role), each with
one local CPU device; jax.distributed + gloo provide rendezvous and the
cross-process collective transport. The worker asserts gradient-allreduce
equivalence against a single-device reference over the global batch —
the same equivalence bar the in-process tests use.
"""

import os

import pytest

from sparkdl_tpu.runner import launcher

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_train_and_collectives(tmp_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # each worker gets exactly ONE local cpu device (the parent test
        # env forces 8 — undo that so global mesh = 2 processes x 1)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    results = launcher.launch(_WORKER, np=2, args=[str(tmp_path)], env=env,
                              timeout_s=420.0, capture=True)
    assert (tmp_path / "rank0.ok").exists(), results[0].stderr[-2000:]
    assert (tmp_path / "rank1.ok").exists(), results[1].stderr[-2000:]


def test_launcher_propagates_failures(tmp_path):
    bad = tmp_path / "boom.py"
    bad.write_text("import sys; sys.exit(3)\n")
    with pytest.raises(RuntimeError, match="rank"):
        launcher.launch(str(bad), np=2, timeout_s=60.0, capture=True)


def test_launcher_rejects_bad_np():
    with pytest.raises(ValueError):
        launcher.launch("x.py", np=0)
