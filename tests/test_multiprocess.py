"""Multi-process XlaRunner proof (SURVEY.md §2.5/§3.5 hard-part #1;
round-1 verdict item 2) + gang-supervision tests (ISSUE 1 tentpole).

Spawns 2 REAL OS processes via runner.launcher (the mpirun role), each with
one local CPU device; jax.distributed + gloo provide rendezvous and the
cross-process collective transport. The worker asserts gradient-allreduce
equivalence against a single-device reference over the global batch —
the same equivalence bar the in-process tests use.

The supervision tests use tiny jax-free scripts (fast, tier-1) plus one
slow real-training gang where a chaos plan SIGKILLs a rank mid-run.
"""

import os
import sys
import time

import pytest

from sparkdl_tpu.runner import launcher
from sparkdl_tpu.runner.chaos import Fault, FaultPlan
from sparkdl_tpu.runner.launcher import GangFailure, supervise

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")
_CHAOS_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "chaos_mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_train_and_collectives(tmp_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # each worker gets exactly ONE local cpu device (the parent test
        # env forces 8 — undo that so global mesh = 2 processes x 1)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    results = launcher.launch(_WORKER, np=2, args=[str(tmp_path)], env=env,
                              timeout_s=420.0, capture=True)
    assert (tmp_path / "rank0.ok").exists(), results[0].stderr[-2000:]
    assert (tmp_path / "rank1.ok").exists(), results[1].stderr[-2000:]


def test_launcher_propagates_failures(tmp_path):
    bad = tmp_path / "boom.py"
    bad.write_text("import sys; sys.exit(3)\n")
    with pytest.raises(RuntimeError, match="rank"):
        launcher.launch(str(bad), np=2, timeout_s=60.0, capture=True)


def test_launcher_rejects_bad_np():
    with pytest.raises(ValueError):
        launcher.launch("x.py", np=0)
    with pytest.raises(ValueError):
        supervise("x.py", np=0)


class TestGangSupervision:
    """Poll-loop, watchdog, and restart-budget behavior via tiny jax-free
    worker scripts — fast enough for tier-1."""

    def test_dead_rank_detected_within_poll_not_timeout(self, tmp_path):
        """One rank dies while its peer 'hangs on a collective' (sleeps):
        the old sequential wait burned the full timeout_s; the poll loop
        must detect, kill the gang, and raise within seconds."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '1':\n"
            "    print('boom-rank-1', file=sys.stderr)\n"
            "    sys.exit(3)\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25)
        wall = time.monotonic() - t0
        assert wall < 30, f"detection took {wall:.1f}s (poll loop broken?)"
        assert "rank(s) [1]" in str(ei.value)
        assert "boom-rank-1" in str(ei.value)  # salvaged stderr
        assert ei.value.kind == "retryable"

    def test_timeout_salvages_which_rank_stalled(self, tmp_path):
        """On timeout the error must name the rank that stopped making
        progress and carry the completed ranks' output (the postmortem
        the old communicate()-then-raise path threw away)."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0':\n"
            "    print('rank0-finished-cleanly', file=sys.stderr)\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n")
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=4.0, capture=True,
                            poll_s=0.25)
        msg = str(ei.value)
        assert "rank(s) [1] still running" in msg
        assert "rank(s) [0] had exited" in msg
        assert "rank0-finished-cleanly" in msg
        assert ei.value.hung

    def test_watchdog_detects_stale_heartbeat(self, tmp_path):
        """A rank that beats once then stalls must be caught by the
        heartbeat watchdog long before timeout_s."""
        hb = tmp_path / "hb"
        hb.mkdir()
        script = tmp_path / "w.py"
        script.write_text(
            "import os, time\n"
            "d = os.environ['SPARKDL_HEARTBEAT_DIR']\n"
            "r = os.environ['SPARKDL_PROCESS_ID']\n"
            "open(os.path.join(d, 'rank%s.hb' % r), 'w').write('7')\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25,
                            heartbeat_dir=str(hb), watchdog_s=1.5)
        wall = time.monotonic() - t0
        assert wall < 30, f"watchdog took {wall:.1f}s"
        assert ei.value.hung and ei.value.kind == "retryable"
        assert "heartbeat watchdog" in str(ei.value)
        assert "step 7" in str(ei.value)  # where progress stopped

    def test_supervise_restarts_retryable_and_succeeds(self, tmp_path):
        """First attempt dies with an UNAVAILABLE-shaped error; supervise
        must classify retryable, relaunch, and report exactly 1 restart."""
        script = tmp_path / "w.py"
        # Only rank 0 fails (and only once, via the marker): if both ranks
        # raced to fail, the gang kill could reach the slower rank before
        # its marker write and cost a second, nondeterministic restart.
        script.write_text(
            "import os, sys\n"
            "m = sys.argv[1]\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0' "
            "and not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    print('UNAVAILABLE: injected backend flake',"
            " file=sys.stderr)\n"
            "    sys.exit(1)\n")
        res = supervise(str(script), np=2, args=[str(tmp_path / "m")],
                        timeout_s=60.0, max_restarts=2, backoff_s=0.05,
                        poll_s=0.25)
        assert res.restarts == 1 and res.attempts == 2
        assert res.failure_kinds == ["retryable"]
        assert all(r.returncode == 0 for r in res.results)

    def test_supervise_fatal_does_not_retry(self, tmp_path):
        """A ValueError-shaped death must not burn the restart budget."""
        script = tmp_path / "w.py"
        script.write_text(
            "import sys\n"
            "with open(sys.argv[1], 'a') as f: f.write('attempt\\n')\n"
            "raise ValueError('user bug')\n")
        count = tmp_path / "count"
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=2, args=[str(count)], timeout_s=60.0,
                      max_restarts=3, backoff_s=0.05, poll_s=0.25)
        assert ei.value.kind == "fatal"
        # One attempt only (<= 2 writes: the gang kill may reach the
        # slower rank before its append) — a retry would write 3+.
        assert 1 <= count.read_text().count("attempt") <= 2

    def test_supervise_budget_exhaustion(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text("import sys\n"
                          "print('UNAVAILABLE: forever', file=sys.stderr)\n"
                          "sys.exit(1)\n")
        with pytest.raises(GangFailure, match="giving up after 1"):
            supervise(str(script), np=2, timeout_s=60.0, max_restarts=1,
                      backoff_s=0.05, poll_s=0.25)


# Jax-free poison worker: dies with a batch-attributed failure (postmortem
# into SPARKDL_EVENT_DIR, the evidence the timeline correlates on) until
# its poison batch lands on SPARKDL_SKIP_BATCHES, then exits 0. `mode`
# picks the stderr/classification shape: retryable (UNAVAILABLE) or fatal
# (TrainingDivergedError, the NaN-poison signature). `pick` chooses the
# poison batch; "next_unskipped" models a systematically bad dataset
# (a NEW poison appears whenever one is skipped) for the circuit breaker.
_POISON_WORKER = """
import json, os, sys, time
skip = json.loads(os.environ.get("SPARKDL_SKIP_BATCHES", "[]"))
mode = {mode!r}
bi = {pick}
if bi is None:
    sys.exit(0)
d = os.environ["SPARKDL_EVENT_DIR"]
err = ({{"type": "TrainingDivergedError",
        "message": "training diverged: non-finite loss (nan) at step %d" % bi}}
       if mode == "fatal" else
       {{"type": "InjectedPreemption", "message": "UNAVAILABLE: poison"}})
pm = {{"t": time.time(), "rank": 0, "site": "fit", "step": bi,
      "batch_index": bi, "error": err}}
tmp = os.path.join(d, "postmortem_rank0.json.tmp")
open(tmp, "w").write(json.dumps(pm))
os.replace(tmp, os.path.join(d, "postmortem_rank0.json"))
print(err["type"] + ": " + err["message"], file=sys.stderr)
sys.exit(1)
"""


def _poison_script(tmp_path, mode="retryable",
                   pick="8 if 8 not in skip else None"):
    script = tmp_path / "poison.py"
    script.write_text(_POISON_WORKER.format(mode=mode, pick=pick))
    return str(script)


class TestPoisonBatchQuarantine:
    """ISSUE 5 tentpole, supervisor half: consecutive failures at one
    (step, batch_index) quarantine the batch instead of burning the
    restart budget; without the skip-list the same job death-loops (the
    pre-ISSUE-5 counterfactual); SPARKDL_MAX_SKIPPED_BATCHES is the
    circuit breaker. Jax-free workers — fast enough for tier-1; the
    real-training end-to-end is scripts/train_resume_smoke.py (slow)."""

    def test_retryable_poison_quarantined_after_two_failures(self, tmp_path):
        from sparkdl_tpu.runner import metrics
        metrics.run_stats.reset()
        res = supervise(_poison_script(tmp_path), np=1, timeout_s=30.0,
                        max_restarts=1, backoff_s=0.05, poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["retryable", "quarantined"]
        assert res.restarts == 2  # one budgeted + one free quarantine
        names = [d.get("name") for d in res.degradations]
        assert "train_batch_quarantined" in names
        q = next(d for d in res.degradations
                 if d.get("name") == "train_batch_quarantined")
        assert q["batch_index"] == 8 and q["skip_list"] == [8]
        assert metrics.run_stats.train_batches_quarantined == 1
        metrics.run_stats.reset()

    def test_fatal_poison_gets_probe_restart_then_quarantine(self, tmp_path):
        """A batch-attributed FATAL failure (TrainingDivergedError from a
        NaN record) must not give up outright: one budgeted probe restart
        tests determinism, recurrence quarantines."""
        res = supervise(_poison_script(tmp_path, mode="fatal"), np=1,
                        timeout_s=30.0, max_restarts=1, backoff_s=0.05,
                        poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["fatal", "quarantined"]

    def test_fatal_probe_not_blocked_by_earlier_unrelated_signature(
            self, tmp_path):
        """Review regression: a batch-attributed FATAL arriving after an
        unrelated batch-attributed retryable failure must still get its
        probe restart (the old gate required prev_sig to be None, so the
        genuine poison gave up unprobed)."""
        script = tmp_path / "w.py"
        script.write_text("""
import json, os, sys, time
marker, skip = sys.argv[1], json.loads(
    os.environ.get("SPARKDL_SKIP_BATCHES", "[]"))
if not os.path.exists(marker):
    # attempt 1: transient draw flake at batch 3, retryable-shaped
    open(marker, "w").write("x")
    bi, err = 3, {"type": "InjectedPreemption",
                  "message": "UNAVAILABLE: transient flake"}
elif 8 not in skip:
    # attempts 2+: deterministic NaN poison at batch 8, fatal-shaped
    bi, err = 8, {"type": "TrainingDivergedError",
                  "message": "training diverged: non-finite loss (nan)"}
else:
    sys.exit(0)
d = os.environ["SPARKDL_EVENT_DIR"]
pm = {"t": time.time(), "rank": 0, "site": "fit", "step": bi,
      "batch_index": bi, "error": err}
tmp = os.path.join(d, "postmortem_rank0.json.tmp")
open(tmp, "w").write(json.dumps(pm))
os.replace(tmp, os.path.join(d, "postmortem_rank0.json"))
print(err["type"] + ": " + err["message"], file=sys.stderr)
sys.exit(1)
""")
        res = supervise(str(script), np=1, args=[str(tmp_path / "m")],
                        timeout_s=30.0, max_restarts=3, backoff_s=0.05,
                        poll_s=0.2)
        assert res.quarantined_batches == [8]
        assert res.failure_kinds == ["retryable", "fatal", "quarantined"]

    def test_counterfactual_death_loop_without_quarantine(self, tmp_path):
        """The pre-ISSUE-5 behavior, pinned: the identical poison job with
        quarantine_batches=False replays into the same batch every
        attempt and exhausts the restart budget."""
        script = _poison_script(tmp_path, pick="8")  # never recovers
        with pytest.raises(GangFailure, match="giving up after 2"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=2,
                      backoff_s=0.05, poll_s=0.2,
                      quarantine_batches=False)

    def test_batchless_fatal_still_fails_fast(self, tmp_path):
        """A fatal failure with NO batch attribution keeps today's
        immediate give-up — the probe restart is only for failures the
        quarantine could act on."""
        script = tmp_path / "w.py"
        script.write_text(
            "import sys\nraise ValueError('user bug, no batch')\n")
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=1, timeout_s=30.0, max_restarts=3,
                      backoff_s=0.05, poll_s=0.2)
        assert ei.value.kind == "fatal"
        assert "giving up after 0 restart(s)" in str(ei.value)

    def test_unskippable_poison_fails_fast_not_requarantine_loop(
            self, tmp_path):
        """Review regression: a poison the dataset CANNOT skip (draw-time
        raise in a non-seekable source — the worker here keeps dying at
        batch 8 even after it is skip-listed) must not alternate
        quarantine/restart forever: one quarantine attempt, then the
        normal budget policy, no duplicate skip-list entries."""
        script = _poison_script(tmp_path, pick="8")  # ignores skip-list
        with pytest.raises(GangFailure,
                           match=r"giving up after 2 restart\(s\)"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=2,
                      backoff_s=0.05, poll_s=0.2)

    def test_max_skipped_batches_circuit_breaker(self, tmp_path):
        """A dataset that presents a NEW poison batch whenever one is
        skipped is systematically bad: past the cap the supervisor raises
        fatal PoisonDataError instead of eating the dataset."""
        from sparkdl_tpu.runner.failures import PoisonDataError
        script = _poison_script(tmp_path, pick="len(skip)")
        with pytest.raises(PoisonDataError, match="circuit breaker"):
            supervise(script, np=1, timeout_s=30.0, max_restarts=8,
                      backoff_s=0.05, poll_s=0.2, max_skipped_batches=2)


@pytest.mark.slow
@pytest.mark.chaos
def test_supervise_sigkilled_rank_relaunches_to_completion(tmp_path):
    """The acceptance gang test: a chaos plan SIGKILLs rank 1 at step 2 of
    a real 2-process training run. The supervisor must detect the dead
    rank within a poll interval (not the full timeout_s), kill the gang,
    classify retryable, and relaunch to completion within the budget —
    the plan's state_dir guarantees the kill fires only once."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    plan = FaultPlan([Fault("step_start", "sigkill", at_step=2, rank=1)])
    t0 = time.monotonic()
    res = supervise(_CHAOS_WORKER, np=2, args=[str(tmp_path)], env=env,
                    timeout_s=600.0, max_restarts=2, backoff_s=0.1,
                    poll_s=0.5, plan=plan)
    wall = time.monotonic() - t0
    assert res.restarts == 1, res.failure_kinds
    assert res.failure_kinds == ["retryable"]
    assert (tmp_path / "rank0.ok").exists()
    assert (tmp_path / "rank1.ok").exists()
    # Prompt detection: total wall includes 2 full jax startups but must
    # sit far below even ONE timeout_s — the old sequential wait would
    # have burned 600s before noticing the dead rank.
    assert wall < 300, f"supervise took {wall:.0f}s — timeout-driven?"
