"""Multi-process XlaRunner proof (SURVEY.md §2.5/§3.5 hard-part #1;
round-1 verdict item 2) + gang-supervision tests (ISSUE 1 tentpole).

Spawns 2 REAL OS processes via runner.launcher (the mpirun role), each with
one local CPU device; jax.distributed + gloo provide rendezvous and the
cross-process collective transport. The worker asserts gradient-allreduce
equivalence against a single-device reference over the global batch —
the same equivalence bar the in-process tests use.

The supervision tests use tiny jax-free scripts (fast, tier-1) plus one
slow real-training gang where a chaos plan SIGKILLs a rank mid-run.
"""

import os
import sys
import time

import pytest

from sparkdl_tpu.runner import launcher
from sparkdl_tpu.runner.chaos import Fault, FaultPlan
from sparkdl_tpu.runner.launcher import GangFailure, supervise

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")
_CHAOS_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "chaos_mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_train_and_collectives(tmp_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # each worker gets exactly ONE local cpu device (the parent test
        # env forces 8 — undo that so global mesh = 2 processes x 1)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    results = launcher.launch(_WORKER, np=2, args=[str(tmp_path)], env=env,
                              timeout_s=420.0, capture=True)
    assert (tmp_path / "rank0.ok").exists(), results[0].stderr[-2000:]
    assert (tmp_path / "rank1.ok").exists(), results[1].stderr[-2000:]


def test_launcher_propagates_failures(tmp_path):
    bad = tmp_path / "boom.py"
    bad.write_text("import sys; sys.exit(3)\n")
    with pytest.raises(RuntimeError, match="rank"):
        launcher.launch(str(bad), np=2, timeout_s=60.0, capture=True)


def test_launcher_rejects_bad_np():
    with pytest.raises(ValueError):
        launcher.launch("x.py", np=0)
    with pytest.raises(ValueError):
        supervise("x.py", np=0)


class TestGangSupervision:
    """Poll-loop, watchdog, and restart-budget behavior via tiny jax-free
    worker scripts — fast enough for tier-1."""

    def test_dead_rank_detected_within_poll_not_timeout(self, tmp_path):
        """One rank dies while its peer 'hangs on a collective' (sleeps):
        the old sequential wait burned the full timeout_s; the poll loop
        must detect, kill the gang, and raise within seconds."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '1':\n"
            "    print('boom-rank-1', file=sys.stderr)\n"
            "    sys.exit(3)\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25)
        wall = time.monotonic() - t0
        assert wall < 30, f"detection took {wall:.1f}s (poll loop broken?)"
        assert "rank(s) [1]" in str(ei.value)
        assert "boom-rank-1" in str(ei.value)  # salvaged stderr
        assert ei.value.kind == "retryable"

    def test_timeout_salvages_which_rank_stalled(self, tmp_path):
        """On timeout the error must name the rank that stopped making
        progress and carry the completed ranks' output (the postmortem
        the old communicate()-then-raise path threw away)."""
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0':\n"
            "    print('rank0-finished-cleanly', file=sys.stderr)\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n")
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=4.0, capture=True,
                            poll_s=0.25)
        msg = str(ei.value)
        assert "rank(s) [1] still running" in msg
        assert "rank(s) [0] had exited" in msg
        assert "rank0-finished-cleanly" in msg
        assert ei.value.hung

    def test_watchdog_detects_stale_heartbeat(self, tmp_path):
        """A rank that beats once then stalls must be caught by the
        heartbeat watchdog long before timeout_s."""
        hb = tmp_path / "hb"
        hb.mkdir()
        script = tmp_path / "w.py"
        script.write_text(
            "import os, time\n"
            "d = os.environ['SPARKDL_HEARTBEAT_DIR']\n"
            "r = os.environ['SPARKDL_PROCESS_ID']\n"
            "open(os.path.join(d, 'rank%s.hb' % r), 'w').write('7')\n"
            "time.sleep(120)\n")
        t0 = time.monotonic()
        with pytest.raises(GangFailure) as ei:
            launcher.launch(str(script), np=2, timeout_s=120.0,
                            capture=True, poll_s=0.25,
                            heartbeat_dir=str(hb), watchdog_s=1.5)
        wall = time.monotonic() - t0
        assert wall < 30, f"watchdog took {wall:.1f}s"
        assert ei.value.hung and ei.value.kind == "retryable"
        assert "heartbeat watchdog" in str(ei.value)
        assert "step 7" in str(ei.value)  # where progress stopped

    def test_supervise_restarts_retryable_and_succeeds(self, tmp_path):
        """First attempt dies with an UNAVAILABLE-shaped error; supervise
        must classify retryable, relaunch, and report exactly 1 restart."""
        script = tmp_path / "w.py"
        # Only rank 0 fails (and only once, via the marker): if both ranks
        # raced to fail, the gang kill could reach the slower rank before
        # its marker write and cost a second, nondeterministic restart.
        script.write_text(
            "import os, sys\n"
            "m = sys.argv[1]\n"
            "if os.environ['SPARKDL_PROCESS_ID'] == '0' "
            "and not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    print('UNAVAILABLE: injected backend flake',"
            " file=sys.stderr)\n"
            "    sys.exit(1)\n")
        res = supervise(str(script), np=2, args=[str(tmp_path / "m")],
                        timeout_s=60.0, max_restarts=2, backoff_s=0.05,
                        poll_s=0.25)
        assert res.restarts == 1 and res.attempts == 2
        assert res.failure_kinds == ["retryable"]
        assert all(r.returncode == 0 for r in res.results)

    def test_supervise_fatal_does_not_retry(self, tmp_path):
        """A ValueError-shaped death must not burn the restart budget."""
        script = tmp_path / "w.py"
        script.write_text(
            "import sys\n"
            "with open(sys.argv[1], 'a') as f: f.write('attempt\\n')\n"
            "raise ValueError('user bug')\n")
        count = tmp_path / "count"
        with pytest.raises(GangFailure) as ei:
            supervise(str(script), np=2, args=[str(count)], timeout_s=60.0,
                      max_restarts=3, backoff_s=0.05, poll_s=0.25)
        assert ei.value.kind == "fatal"
        # One attempt only (<= 2 writes: the gang kill may reach the
        # slower rank before its append) — a retry would write 3+.
        assert 1 <= count.read_text().count("attempt") <= 2

    def test_supervise_budget_exhaustion(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text("import sys\n"
                          "print('UNAVAILABLE: forever', file=sys.stderr)\n"
                          "sys.exit(1)\n")
        with pytest.raises(GangFailure, match="giving up after 1"):
            supervise(str(script), np=2, timeout_s=60.0, max_restarts=1,
                      backoff_s=0.05, poll_s=0.25)


@pytest.mark.slow
@pytest.mark.chaos
def test_supervise_sigkilled_rank_relaunches_to_completion(tmp_path):
    """The acceptance gang test: a chaos plan SIGKILLs rank 1 at step 2 of
    a real 2-process training run. The supervisor must detect the dead
    rank within a poll interval (not the full timeout_s), kill the gang,
    classify retryable, and relaunch to completion within the budget —
    the plan's state_dir guarantees the kill fires only once."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    plan = FaultPlan([Fault("step_start", "sigkill", at_step=2, rank=1)])
    t0 = time.monotonic()
    res = supervise(_CHAOS_WORKER, np=2, args=[str(tmp_path)], env=env,
                    timeout_s=600.0, max_restarts=2, backoff_s=0.1,
                    poll_s=0.5, plan=plan)
    wall = time.monotonic() - t0
    assert res.restarts == 1, res.failure_kinds
    assert res.failure_kinds == ["retryable"]
    assert (tmp_path / "rank0.ok").exists()
    assert (tmp_path / "rank1.ok").exists()
    # Prompt detection: total wall includes 2 full jax startups but must
    # sit far below even ONE timeout_s — the old sequential wait would
    # have burned 600s before noticing the dead rank.
    assert wall < 300, f"supervise took {wall:.0f}s — timeout-driven?"
