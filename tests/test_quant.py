"""Quantized serving (ISSUE 18): int8/fp8 block-quantized paged KV
fused into the flash-decode kernel, plus int8 projection weights.

Layers, leanest first: the `_quant_insert_rows` scale discipline
(round-trip error bounded by ½ LSB of the per-block scale — the
documented tolerance gate; scale reset on block reuse; requant when a
later row grows a block's amax), the `support_reason` contract (every
stand-down names WHY — the boolean `supports` twin never disagrees),
the fused-dequant kernel's equivalence to the dequantized gather view
in interpret mode (S=1 decode and the S=k+1 verify window), the
`QuantDense` int8 weight path (absmax per-output-channel), and the
backend-level fallback regression (an unsupported block size serves
through the dense gather view and the log says why).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdl_tpu.models import llama as L
from sparkdl_tpu.ops import flash_decode as fd
from sparkdl_tpu.ops import paged_flash_decode as pfd

# ---------------------------------------------------------------------------
# scale discipline (_quant_insert_rows)
# ---------------------------------------------------------------------------


def _fresh(pool=6, hkv=2, bs=8, d=16, name="int8"):
    qdt, _ = L.kv_quant_spec(name)
    codes = jnp.zeros((pool, hkv, bs, d), qdt)
    plane = jnp.zeros((pool, hkv, 2), jnp.float32)
    return codes, plane


class TestQuantInsertRows:
    @pytest.mark.parametrize("name", sorted(L.KV_QUANT_DTYPES))
    def test_round_trip_error_within_documented_gate(self, name):
        """THE tolerance gate the README documents: after quantizing a
        full block of rows, dequantized values sit within ½ LSB of the
        block scale for int8 (round-to-nearest of codes), and within
        an e4m3 mantissa step (2^-3 relative, plus the absmax scale)
        for fp8."""
        rng = np.random.RandomState(0)
        codes, plane = _fresh(name=name)
        bs, hkv, d = 8, 2, 16
        rows = jnp.asarray(rng.randn(bs, hkv, d), jnp.float32) * 3.0
        blk = jnp.full((bs,), 2, jnp.int32)
        off = jnp.arange(bs, dtype=jnp.int32)
        codes, plane = L._quant_insert_rows(codes, plane, 0, blk, off,
                                            rows)
        scale = np.asarray(plane)[2, :, 0]              # [Hkv]
        got = np.asarray(codes)[2].astype(np.float32) \
            * scale[:, None, None]                      # [Hkv, bs, d]
        want = np.transpose(np.asarray(rows), (1, 0, 2))
        err = np.abs(got - want)
        if name == "int8":
            assert (err <= 0.5 * scale[:, None, None] + 1e-7).all()
        else:  # fp8 e4m3: relative mantissa step, scaled
            amax = np.abs(want).max(axis=(1, 2), keepdims=True)
            assert (err <= amax * 2.0 ** -3).all()
        # the scale is the block absmax over qmax — no clipping happened
        qmax = L.kv_quant_spec(name)[1]
        np.testing.assert_allclose(
            scale, want.reshape(hkv, -1).__abs__().max(-1) / qmax,
            rtol=1e-6)

    def test_scale_grows_and_resident_rows_requantize(self):
        """A later row with a larger absmax grows the shared block
        scale; rows already resident requantize by old/new — still
        within ½ NEW LSB of their original values."""
        codes, plane = _fresh()
        small = jnp.ones((1, 2, 16), jnp.float32) * 0.5
        big = jnp.ones((1, 2, 16), jnp.float32) * 8.0
        blk = jnp.asarray([3], jnp.int32)
        codes, plane = L._quant_insert_rows(
            codes, plane, 1, blk, jnp.asarray([0], jnp.int32), small)
        s0 = float(plane[3, 0, 1])
        codes, plane = L._quant_insert_rows(
            codes, plane, 1, blk, jnp.asarray([1], jnp.int32), big)
        s1 = float(plane[3, 0, 1])
        assert s1 > s0
        deq = np.asarray(codes)[3, :, 0].astype(np.float32) * s1
        assert np.abs(deq - 0.5).max() <= 0.5 * s1 + 1e-7
        deq1 = np.asarray(codes)[3, :, 1].astype(np.float32) * s1
        assert np.abs(deq1 - 8.0).max() <= 0.5 * s1 + 1e-7

    def test_block_reuse_resets_scale_not_inherits(self):
        """An off == 0 write is a block's FIRST row (write-frontier
        invariant): a freed-then-reallocated block must take the NEW
        tenant's scale, not keep amplifying under the old one."""
        codes, plane = _fresh()
        blk = jnp.asarray([4], jnp.int32)
        codes, plane = L._quant_insert_rows(
            codes, plane, 0, blk, jnp.asarray([0], jnp.int32),
            jnp.ones((1, 2, 16), jnp.float32) * 100.0)
        assert float(plane[4, 0, 0]) == pytest.approx(100.0 / 127.0)
        codes, plane = L._quant_insert_rows(
            codes, plane, 0, blk, jnp.asarray([0], jnp.int32),
            jnp.ones((1, 2, 16), jnp.float32) * 0.25)
        assert float(plane[4, 0, 0]) == pytest.approx(0.25 / 127.0)
        deq = float(codes[4, 0, 0, 0]) * float(plane[4, 0, 0])
        assert deq == pytest.approx(0.25, abs=0.5 * 0.25 / 127.0)

    def test_gather_dequant_matches_manual(self):
        rng = np.random.RandomState(5)
        codes, plane = _fresh()
        rows = jnp.asarray(rng.randn(8, 2, 16), jnp.float32)
        blk = jnp.asarray([1] * 4 + [5] * 4, jnp.int32)
        off = jnp.asarray([0, 1, 2, 3] * 2, jnp.int32)
        codes, plane = L._quant_insert_rows(codes, plane, 0, blk, off,
                                            rows)
        tables = jnp.asarray([[1, 5, 0]], jnp.int32)
        got = np.asarray(L._gather_dequant(codes, plane, 0, tables,
                                           jnp.float32))
        c = np.asarray(codes)
        s = np.asarray(plane)[..., 0]
        manual = np.concatenate(
            [c[b].astype(np.float32) * s[b][:, None, None]
             for b in (1, 5, 0)], axis=1)[None]
        np.testing.assert_allclose(got, manual, rtol=1e-6)


# ---------------------------------------------------------------------------
# support_reason contract (ISSUE 18 satellite: stand-downs name WHY)
# ---------------------------------------------------------------------------


class TestSupportReason:
    def test_paged_reasons_and_boolean_twin_agree(self):
        assert pfd.support_reason(16) is None
        assert pfd.support_reason(16, kv_dtype="int8") is None
        r = pfd.support_reason(12)
        assert r is not None and "12" in r and "8-multiple" in r
        r = pfd.support_reason(16, kv_dtype="int3")
        assert r is not None and "int3" in r and "available" in r
        for bs, kv in ((16, None), (12, None), (16, "int8"),
                       (16, "nope"), (7, None)):
            assert pfd.supports(bs, kv) == \
                (pfd.support_reason(bs, kv) is None)

    def test_dense_reasons_and_boolean_twin_agree(self):
        assert fd.support_reason(256) is None
        r = fd.support_reason(100)
        assert r is not None and "100" in r
        for ml in (256, 100, 64, 130):
            assert fd.supports(ml) == (fd.support_reason(ml) is None)

    def test_backend_stand_down_logs_the_reason(self, caplog):
        """The fallback regression: a paged backend at a block size the
        kernel cannot take still serves (dense gather view) and the
        construction log NAMES the reason — 'dense attention was
        chosen' never again without a why."""
        from sparkdl_tpu.serving import GenerationEngine

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        with caplog.at_level(logging.INFO, "sparkdl_tpu.serving"):
            eng = GenerationEngine.from_model(
                model, variables, num_slots=1, max_len=24,
                block_size=12, kv_dtype="int8")
        msgs = [r.getMessage() for r in caplog.records
                if "stands down" in r.getMessage()]
        assert msgs and "8-multiple" in msgs[0]
        h = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run_until_idle()
        assert len(h.result(1)) == 4  # served through the gather view


# ---------------------------------------------------------------------------
# fused-dequant kernel vs the dequantized gather view (interpret mode)
# ---------------------------------------------------------------------------


def _quantized_pool(seed=0, *, hkv=2, bs=8, mb=3, pool=7, d=16):
    """An adversarial quantized layout built through the REAL insert
    routine: non-contiguous live blocks, a trash-parked slot, mixed
    fills — the paged-flash-decode test harness shape, quantized."""
    rng = np.random.RandomState(seed)
    k_codes, k_plane = _fresh(pool, hkv, bs, d)
    v_codes, _ = _fresh(pool, hkv, bs, d)
    plane = k_plane
    tables = np.zeros((3, mb), np.int32)
    tables[0] = [5, 2, 0]
    tables[1] = [3, 1, 6]
    tables[2] = 0                       # trash-parked
    cur = np.asarray([11, 22, 0], np.int32)
    pads = np.asarray([0, 4, 0], np.int32)
    for slot in range(2):
        for p in range(int(cur[slot])):
            blk = jnp.asarray([tables[slot][p // bs]], jnp.int32)
            off = jnp.asarray([p % bs], jnp.int32)
            kr = jnp.asarray(rng.randn(1, hkv, d), jnp.float32)
            vr = jnp.asarray(rng.randn(1, hkv, d), jnp.float32)
            k_codes, plane = L._quant_insert_rows(k_codes, plane, 0,
                                                  blk, off, kr)
            v_codes, plane = L._quant_insert_rows(v_codes, plane, 1,
                                                  blk, off, vr)
    return (k_codes, v_codes, plane, jnp.asarray(tables),
            jnp.asarray(cur), jnp.asarray(pads))


class TestQuantKernelParity:
    @pytest.mark.parametrize("s_q", [1, 3])
    def test_kernel_equals_dequant_gather_reference(self, s_q):
        """Decode (S=1) and the speculative verify window (S=k+1): the
        fused-dequant paged kernel must match dense flash-decode over
        the DEQUANTIZED gather view. The fold point differs (kernel
        scales after each dot, reference before), so the pin is
        allclose at float-assoc tolerance, not bitwise."""
        k_codes, v_codes, plane, tables, cur, pads = _quantized_pool()
        hkv, bs, d = 2, 8, 16
        q = jnp.asarray(np.random.RandomState(9).randn(
            3, hkv * 2, s_q, d), jnp.float32)
        got = pfd.paged_flash_decode(q, k_codes, v_codes, tables, cur,
                                     pads, kv_scales=plane,
                                     interpret=True)
        kg = L._gather_dequant(k_codes, plane, 0, tables, jnp.float32)
        vg = L._gather_dequant(v_codes, plane, 1, tables, jnp.float32)
        want = jnp.concatenate(
            [fd.flash_decode(q[:, :, i:i + 1], kg, vg, cur + i + 1,
                             pads, block_k=bs, interpret=True)
             for i in range(s_q)], axis=2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)
        assert np.isfinite(np.asarray(got[2])).all()  # trash-parked

    def test_quantized_pool_requires_scales(self):
        k_codes, v_codes, plane, tables, cur, pads = _quantized_pool()
        q = jnp.zeros((3, 4, 1, 16), jnp.float32)
        with pytest.raises(ValueError, match="kv_scales"):
            pfd.paged_flash_decode(q, k_codes, v_codes, tables, cur,
                                   pads, interpret=True)


# ---------------------------------------------------------------------------
# int8 weights (QuantDense / quantize_params)
# ---------------------------------------------------------------------------


class TestWeightQuant:
    def _model(self):
        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        return cfg, model, variables

    def test_quantize_params_targets_and_shapes(self):
        _, model, variables = self._model()
        qp = L.quantize_params(variables["params"], "int8")
        seen = set()
        def walk(tree, path=""):
            for k, v in tree.items():
                p = f"{path}/{k}"
                if isinstance(v, dict) and "kernel" in v:
                    name = path.rsplit("/", 1)[-1] if k == "base" else k
                    kern = v["kernel"]
                    if name in L.WEIGHT_QUANT_TARGETS:
                        seen.add(name)
                        assert kern.dtype == jnp.int8, p
                        assert v["kernel_scale"].shape == \
                            (kern.shape[1],), p
                    else:
                        assert kern.dtype != jnp.int8, p
                if isinstance(v, dict):
                    walk(v, p)
        walk(qp)
        assert seen == set(L.WEIGHT_QUANT_TARGETS)

    def test_int8_forward_close_to_f32_and_float_params_exact(self):
        """The quantized model tracks the f32 model within absmax-
        per-channel int8 error; the SAME quantized-model clone fed
        UNCONVERTED float params takes the plain dense path and matches
        the f32 model bitwise (graceful unconverted checkpoint)."""
        cfg, model, variables = self._model()
        ids = np.random.RandomState(3).randint(
            0, cfg.vocab_size, (2, 6)).astype(np.int32)
        ref = model.apply(variables, ids)
        qmodel = model.clone(weight_quant="int8")
        qp = {"params": L.quantize_params(variables["params"], "int8")}
        out = qmodel.apply(qp, ids)
        assert np.allclose(np.asarray(out), np.asarray(ref),
                           atol=0.15, rtol=0.1)
        # greedy next-token argmax survives quantization on the tiny
        same = (np.asarray(out[:, -1]).argmax(-1)
                == np.asarray(ref[:, -1]).argmax(-1))
        assert same.all()
        exact = qmodel.apply(variables, ids)  # float params, quant model
        np.testing.assert_array_equal(np.asarray(exact),
                                      np.asarray(ref))


# ---------------------------------------------------------------------------
# engine-level guards
# ---------------------------------------------------------------------------


class TestEngineGuards:
    def test_kv_dtype_without_paging_raises(self):
        from sparkdl_tpu.serving import GenerationEngine

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        with pytest.raises(ValueError, match="paged"):
            GenerationEngine.from_model(model, variables, num_slots=1,
                                        max_len=32, kv_dtype="int8")

    def test_unknown_dtypes_raise_loudly(self):
        with pytest.raises(ValueError, match="available"):
            L.kv_quant_spec("int4")
        from sparkdl_tpu.serving.backend import PagedLlamaSlotBackend
        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        with pytest.raises(ValueError, match="int4"):
            PagedLlamaSlotBackend(model, variables, 1, 32,
                                  kv_dtype="int4")
