#!/usr/bin/env python
"""Machine-check the BENCH_r*.json trajectory (ISSUE 17 satellite).

Each growth round appends a ``BENCH_r{N}.json`` record; nothing so far
*reads* the sequence, so a regression only surfaces when a human eyeballs
two files. This script loads every round, extracts per-metric series
(the headline ``parsed.value`` keyed by ``parsed.metric``, plus every
numeric scalar in ``parsed.extra`` — mfu, step_time_s, serve_tokens_s,
stall ratios, ...), prints a trend table, and **exits nonzero when the
newest valid value regressed past ``--threshold`` versus the best prior
valid value**.

Rounds where the harness never reached a measurement — ``parsed`` null
(rc 124 timeouts) or ``parsed.error`` set (``backend_unavailable``
probes) — are *excluded from regression endpoints* and annotated in the
table instead: a CPU-only container scoring 0.0 img/s must read as "no
evidence", not as a 100% regression.

Direction is inferred from the metric name: stall/latency/ttft/
step-time-shaped names are lower-is-better; everything else (throughput,
mfu, hit rates, speedups) higher-is-better.

Usage:
    python scripts/bench_trend.py [--dir REPO] [--threshold 0.15]
        [--json]

Exit codes: 0 = no regression; 1 = regression past threshold;
2 = fewer than two valid rounds (no trend to check).
"""

import argparse
import glob
import json
import os
import re
import sys

# Metric-name shapes where smaller numbers are better. Everything else
# is treated as higher-is-better (throughput, mfu, hit rate, speedup).
_LOWER_IS_BETTER = re.compile(
    r"(stall|latency|ttft|step_time|_time_s$|_s$|_ratio$|skew)", re.I)
# extra[] keys that are config/identity, not measurements.
_NON_METRIC_EXTRA = ("n_chips", "batch_per_chip", "steps", "image_size",
                     "seq_len", "budget")


def _valid(parsed) -> bool:
    """A round counts as measurement evidence only when the harness
    actually measured: parsed present and no probe error recorded."""
    return isinstance(parsed, dict) and not parsed.get("error")


def _series(records: list[dict]) -> dict:
    """``{metric_name: [(round_n, value), ...]}`` over valid rounds."""
    out: dict = {}
    for rec in records:
        parsed = rec.get("parsed")
        if not _valid(parsed):
            continue
        n = rec.get("n")
        vals = {}
        if isinstance(parsed.get("value"), (int, float)) \
                and parsed.get("metric"):
            vals[str(parsed["metric"])] = float(parsed["value"])
        for k, v in (parsed.get("extra") or {}).items():
            if k in _NON_METRIC_EXTRA:
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                vals[k] = float(v)
        for k, v in vals.items():
            out.setdefault(k, []).append((n, v))
    return out


def trend(records: list[dict], threshold: float = 0.15) -> dict:
    """Pure trend computation (the synthetic test drives this directly).

    For each metric with >= 2 valid points: compare the LATEST valid
    value against the BEST prior valid value (min for lower-is-better
    names, max otherwise). ``change`` > 0 means worse. A metric regresses
    when change > threshold.
    """
    rounds = sorted(records, key=lambda r: r.get("n", 0))
    skipped = [{"n": r.get("n"),
                "reason": "no parse" if not isinstance(r.get("parsed"),
                                                       dict)
                else str((r["parsed"].get("error") or {}).get(
                    "kind", "error"))}
               for r in rounds if not _valid(r.get("parsed"))]
    metrics = []
    for name, pts in sorted(_series(rounds).items()):
        lower = bool(_LOWER_IS_BETTER.search(name))
        last_n, last = pts[-1]
        entry = {"metric": name, "direction":
                 "lower" if lower else "higher",
                 "points": len(pts), "latest_round": last_n,
                 "latest": last}
        if len(pts) < 2:
            entry["change"] = None
        else:
            prior = [v for _, v in pts[:-1]]
            best = min(prior) if lower else max(prior)
            entry["best_prior"] = best
            if best == 0.0:
                # can't express relative change off a zero baseline
                entry["change"] = None
            else:
                chg = (last - best) / abs(best)
                entry["change"] = round(chg if lower else -chg, 4)
        entry["regressed"] = bool(entry["change"] is not None
                                  and entry["change"] > threshold)
        metrics.append(entry)
    regressions = [m for m in metrics if m["regressed"]]
    return {"rounds": len(rounds), "valid_rounds":
            len(rounds) - len(skipped), "skipped": skipped,
            "threshold": threshold, "metrics": metrics,
            "regressions": [m["metric"] for m in regressions],
            "ok": not regressions}


def load_records(repo_dir: str) -> list[dict]:
    recs = []
    for path in sorted(glob.glob(os.path.join(repo_dir,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                recs.append(json.load(f))
        except (OSError, ValueError):
            continue
    return recs


def _table(rep: dict) -> str:
    lines = [f"bench_trend: {rep['valid_rounds']}/{rep['rounds']} "
             f"rounds measured, threshold "
             f"{rep['threshold'] * 100:.0f}%"]
    for s in rep["skipped"]:
        lines.append(f"  r{s['n']:02d}: skipped ({s['reason']})")
    w = max((len(m["metric"]) for m in rep["metrics"]), default=6)
    for m in rep["metrics"]:
        chg = ("    --" if m["change"] is None
               else f"{m['change'] * +100:+6.1f}%")
        flag = "  << REGRESSED" if m["regressed"] else ""
        lines.append(f"  {m['metric']:<{w}}  ({m['direction'][0]}) "
                     f"n={m['points']:<2d} latest={m['latest']:<12.6g} "
                     f"worse-by={chg}{flag}")
    lines.append("bench_trend: " + ("OK" if rep["ok"] else
                                    f"REGRESSION: "
                                    f"{', '.join(rep['regressions'])}"))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Trend table + regression gate over BENCH_r*.json")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="repo dir holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative worsening that fails the gate "
                         "(default 0.15 = 15%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report instead of "
                         "the table")
    ns = ap.parse_args(argv)

    recs = load_records(ns.dir)
    rep = trend(recs, threshold=ns.threshold)
    if ns.json:
        print(json.dumps(rep, default=str))
    else:
        print(_table(rep))
    if rep["valid_rounds"] < 2:
        print("bench_trend: fewer than two measured rounds — no trend "
              "to check", file=sys.stderr)
        return 2
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
