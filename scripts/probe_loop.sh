#!/usr/bin/env bash
# Round-long TPU liveness probe loop (VERDICT r4 task #1).
#
# Probes the axon backend every PROBE_INTERVAL_S (default 600s) with a
# PROBE_TIMEOUT_S (default 120s) timeout, appending one line per attempt to
# PROBE_LOG at the repo root:
#   <iso8601> <up|down|error> <elapsed_s>[ <detail>]
# On the FIRST success it immediately runs scripts/measure_on_tpu.sh, saving
# stdout to BENCH_TPU_MEASURED.json and the full log to MEASURE_LOG, then
# keeps probing (cheaply) so the log also records how long the window lasted.
#
# Usage: nohup bash scripts/probe_loop.sh >/dev/null 2>&1 &
set -u
cd "$(dirname "$0")/.."

INTERVAL="${PROBE_INTERVAL_S:-600}"
TIMEOUT="${PROBE_TIMEOUT_S:-120}"
LOG="PROBE_LOG"
MEASURED_MARK=".probe_measured"
# Default the output to the NEXT FREE BENCH_TPU_MEASURED<N>.json index:
# bench.py's _last_measured_summary ranks records by filename index
# (unnumbered == 1 == oldest, git does not preserve mtimes), so writing a
# new window to the unnumbered name would rank it oldest — or clobber the
# first window's record.
MEASURED_OUT="${PROBE_MEASURED_OUT:-}"
if [ -z "$MEASURED_OUT" ]; then
    MEASURED_OUT="BENCH_TPU_MEASURED.json"
    n=2
    while [ -e "$MEASURED_OUT" ]; do
        MEASURED_OUT="BENCH_TPU_MEASURED${n}.json"
        n=$((n+1))
    done
fi

while true; do
    start=$(date +%s)
    out=$(timeout "$TIMEOUT" python -c "import jax; d=jax.devices(); print(len(d), d[0].platform, getattr(d[0],'device_kind','?'))" 2>&1)
    rc=$?
    elapsed=$(( $(date +%s) - start ))
    ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    if [ $rc -eq 0 ]; then
        echo "$ts up ${elapsed}s $(echo "$out" | tail -1)" >> "$LOG"
        if [ ! -f "$MEASURED_MARK" ]; then
            echo "$ts measuring" >> "$LOG"
            # Stage to a temp file: an aborted/killed measure (the rc=143
            # events in PROBE_LOG) must never clobber an earlier window's
            # good record with partial output.
            bash scripts/measure_on_tpu.sh > "$MEASURED_OUT.tmp" 2> MEASURE_LOG
            mrc=$?
            echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) measure_done rc=$mrc" >> "$LOG"
            if [ $mrc -eq 0 ]; then
                mv "$MEASURED_OUT.tmp" "$MEASURED_OUT"
                touch "$MEASURED_MARK"
            else
                mv "$MEASURED_OUT.tmp" "$MEASURED_OUT.failed" 2>/dev/null
            fi
        fi
    elif [ $rc -eq 124 ]; then
        echo "$ts down ${elapsed}s probe-hung" >> "$LOG"
    else
        echo "$ts down ${elapsed}s rc=$rc $(echo "$out" | grep -v Warning | tail -1 | cut -c1-120)" >> "$LOG"
    fi
    sleep "$INTERVAL"
done
