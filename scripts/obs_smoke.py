#!/usr/bin/env python
"""Observability smoke: flight recorder + gang-timeline postmortem +
live telemetry plane, end-to-end on CPU (ISSUE 2 + ISSUE 6 satellites).

Leg 1 (postmortem): ``supervise(max_restarts=0)`` launches a single-rank
training worker with the flight recorder armed (``SPARKDL_EVENT_DIR`` is
injected by the supervisor) and a ``FaultPlan`` that raises an
UNAVAILABLE-shaped preemption at step 3. The worker dies; ``fit()``'s
failure path flushes a crash postmortem; the supervisor merges the rank's
event stream, postmortem, and heartbeat into ``gang_timeline.json`` and
raises a :class:`GangFailure` carrying it. Asserts the merged postmortem
names the faulted rank, its last step, and the chaos site.

Leg 2 (live telemetry, ISSUE 6): drives a small streamed-scoring run
(deliberately decode-bound) with ``SPARKDL_METRICS_DIR`` armed, asserts a
live per-rank snapshot file appears MID-run (before the stream
finishes), then runs ``scripts/bottleneck_report.py`` over the span
streams + snapshots and asserts it names ``decode`` — the expected
host-side stage — as the bottleneck with internally consistent busy
fractions. Second half (ISSUE 7): the same report over a REAL
image-scoring run — the workload whose Arrow decode/pack/resize was the
pre-ISSUE-7 bottleneck — must no longer name ``decode`` dominant: the
fused feed ships zero-copy uint8 views and the compiled program does
flip/cast/resize, so decode time collapses and attribution moves to the
device stages.

Leg 3 (causal trace, ISSUE 17): a supervised 2-rank gang runs under a
supervisor-minted trace id, then a stub-backend engine serves requests
under the same trace; ``scripts/trace_export.py --validate`` must merge
both ranks' streams, the serving request spans, and telemetry gauge
histories into one Chrome trace where every span carries the run's
trace_id with a parent chain resolving to the run root.

Prints one JSON line; exits 0 iff all legs held.

Run: ``JAX_PLATFORMS=cpu python scripts/obs_smoke.py``
"""

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The supervisor never queries devices, so no jax backend is initialized
# in this process — the workers own the chips.
from sparkdl_tpu.runner.chaos import Fault, FaultPlan  # noqa: E402
from sparkdl_tpu.runner.events import GANG_TIMELINE_FILE  # noqa: E402
from sparkdl_tpu.runner.launcher import GangFailure, supervise  # noqa: E402

_WORKER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import optax
from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

out_dir = sys.argv[1]
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
rng = np.random.RandomState(0)
params = {{"w": rng.randn(4, 3).astype(np.float32)}}

def data():
    r = np.random.RandomState(1)
    while True:
        yield {{"image": r.randn(8, 4).astype(np.float32),
               "label": r.randint(0, 3, (8,))}}

runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"], data=data(), num_steps=6,
    checkpoint_every=2, log_every=100))
"""


def _scoring_leg(out_dir: str) -> dict:
    """ISSUE 6: streamed scoring with the telemetry plane armed from the
    environment, live-snapshot-mid-run assertion, bottleneck report.
    Imports jax — runs AFTER the supervise leg (whose process must stay
    backend-free until its workers own the chips)."""
    import subprocess
    import time

    metrics_dir = os.path.join(out_dir, "metrics")
    event_dir = os.path.join(out_dir, "score_events")
    os.environ["SPARKDL_METRICS_DIR"] = metrics_dir
    os.environ["SPARKDL_METRICS_INTERVAL_S"] = "0.05"
    os.environ["SPARKDL_EVENT_DIR"] = event_dir
    try:
        import numpy as np
        import pyarrow as pa

        from sparkdl_tpu.core.runtime import BatchRunner
        from sparkdl_tpu.transformers.streaming import StreamScorer

        n_chunks, rows = 40, 4

        def make_decoder(rb):
            def decode(start, length):
                time.sleep(0.02)  # decode-bound by construction: the
                return np.full((length, 3), float(start), np.float32)
            return decode          # report must name this stage

        scorer = StreamScorer(
            BatchRunner(lambda b: b * 2.0, batch_size=rows), "y",
            make_decoder=make_decoder,
            encode=lambda r: pa.array([float(v) for v in r[:, 0]],
                                      type=pa.float64()),
            empty_array=lambda: pa.array([], type=pa.float64()),
            chunk_rows=rows, decode_workers=2)
        batches = [pa.RecordBatch.from_arrays(
            [pa.array([float(i)] * rows)], ["x"]) for i in range(n_chunks)]
        snap_path = os.path.join(metrics_dir, "metrics_rank0.json")
        first_seen_at = None
        n_out = 0
        for _ in scorer(iter(batches)):
            n_out += 1
            if first_seen_at is None and os.path.exists(snap_path):
                first_seen_at = n_out  # live snapshot, mid-run
        from sparkdl_tpu.runner import telemetry
        telemetry.stop()  # final flush so the report sees exact books

        report = {}
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "bottleneck_report.py"),
             event_dir, "--metrics-dir", metrics_dir, "--json"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                if line.startswith("{"):
                    report = json.loads(line)
                    break
        rep = report.get("report") or {}
        stages = rep.get("stages") or {}
        fracs_consistent = bool(stages) and all(
            0.0 <= st.get("busy_frac", -1) <= 1.0 for st in stages.values())
        return {
            "scored_rows": n_out * rows,
            "snapshot_mid_run": first_seen_at is not None
            and first_seen_at < n_chunks,
            "snapshot_first_seen_at_batch": first_seen_at,
            "report_rc": proc.returncode,
            "dominant_stage": rep.get("dominant_stage"),
            "dominant_busy_frac": rep.get("dominant_busy_frac"),
            "max_speedup_fixing_others":
                rep.get("max_speedup_fixing_others"),
            "busy_fracs_consistent": fracs_consistent,
            "gang_metrics_ranks":
                (report.get("gang_metrics") or {}).get("n_ranks"),
            "ok": first_seen_at is not None and first_seen_at < n_chunks
            and n_out == n_chunks
            and rep.get("dominant_stage") == "decode"
            and fracs_consistent,
        }
    finally:
        for v in ("SPARKDL_METRICS_DIR", "SPARKDL_METRICS_INTERVAL_S",
                  "SPARKDL_EVENT_DIR"):
            os.environ.pop(v, None)


def _ingest_leg(out_dir: str) -> dict:
    """ISSUE 7: the decode-bound workload the host-ingest PR attacked —
    uniform uint8 image column through ``XlaImageTransformer`` — must NO
    LONGER attribute to ``decode``: the fused feed ships zero-copy views
    (near-zero host decode) and the compiled prologue does
    flip/cast/resize, so the report names a device stage instead."""
    import subprocess

    event_dir = os.path.join(out_dir, "ingest_events")
    os.environ["SPARKDL_EVENT_DIR"] = event_dir
    try:
        import numpy as np
        import pyarrow as pa

        import sparkdl_tpu as sdl
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.runner import events

        events.reset()  # re-arm the stream on the fresh event dir
        rng = np.random.default_rng(0)
        structs = [imageIO.imageArrayToStruct(
            rng.integers(0, 256, (8, 8, 3), np.uint8), origin=f"m{i}")
            for i in range(64)]
        df = sdl.DataFrame.fromArrow(
            pa.table({"image": pa.array(structs,
                                        type=imageIO.imageSchema)}),
            numPartitions=2)
        t = sdl.XlaImageTransformer(
            inputCol="image", outputCol="feat",
            fn=lambda b: b.mean(axis=(1, 2)), inputSize=(16, 16),
            batchSize=8)
        n_rows = len(t.transform(df).collect())
        events.reset()  # close the stream so the report reads full books
    finally:
        os.environ.pop("SPARKDL_EVENT_DIR", None)

    report = {}
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "bottleneck_report.py"),
         event_dir, "--json"],
        capture_output=True, text=True, timeout=120)
    if proc.returncode == 0:
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                report = json.loads(line)
                break
    rep = report.get("report") or {}
    stages = rep.get("stages") or {}
    decode_frac = (stages.get("decode") or {}).get("busy_frac")
    return {
        "scored_rows": n_rows,
        "report_rc": proc.returncode,
        "dominant_stage": rep.get("dominant_stage"),
        "decode_busy_frac": decode_frac,
        "ok": n_rows == 64
        and rep.get("dominant_stage") is not None
        and rep.get("dominant_stage") != "decode",
    }


def _serving_leg(out_dir: str) -> dict:
    """ISSUE 13: a stub-backend engine under load with the plane armed —
    ``/serving`` must answer MID-run with a live slot map; afterwards
    ``request_report.py`` must name the dominant phase of the slowest
    request; the SLO monitor must report compliance >= 0.99 on the
    healthy leg and flip the burn-rate gauge (+ breach event) on an
    injected-slowness leg. Jax-free throughout (StubBackend)."""
    import subprocess
    import time
    import urllib.request

    metrics_dir = os.path.join(out_dir, "serve_metrics")
    event_dir = os.path.join(out_dir, "serve_events")
    os.environ["SPARKDL_EVENT_DIR"] = event_dir
    os.environ["SPARKDL_SLO_TTFT_S"] = "0.5"
    os.environ["SPARKDL_SLO_LATENCY_S"] = "30"
    os.environ["SPARKDL_SLO_WINDOWS_S"] = "1,5"
    os.environ["SPARKDL_METRICS_INTERVAL_S"] = "0.1"
    try:
        from sparkdl_tpu.runner import events, slo, telemetry
        from sparkdl_tpu.serving import GenerationEngine, StubBackend

        events.reset()
        slo.reset()
        telemetry.reset()
        telemetry.start(metrics_dir=metrics_dir, port=0)
        port = telemetry.server_port()

        # -- healthy leg: fast stub, a burst larger than the slot table
        # (the tail's dominant phase is queue wait — attribution food)
        eng = GenerationEngine(StubBackend(4, 128, step_s=0.002),
                               prefill_chunk=8)
        eng.start()
        handles = [eng.submit([1 + i, 2, 3], max_new_tokens=16)
                   for i in range(24)]
        live = None
        deadline = time.time() + 30
        while time.time() < deadline and live is None:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/serving",
                        timeout=5) as resp:
                    body = json.loads(resp.read().decode())
            except OSError:
                break
            engines = body.get("engines") or []
            if engines and engines[0].get("slots_busy", 0) > 0:
                live = engines[0]  # a live slot map, mid-run
            else:
                time.sleep(0.005)
        for h in handles:
            h.wait(60)
        eng.stop(drain=True, timeout=30)
        healthy_slo = (telemetry.snapshot().get("slo") or {}) \
            .get("objectives", {}).get("ttft", {})

        # -- chaos leg: injected slowness — each prefill chunk sleeps
        # 0.8 s, so every TTFT blows the 0.5 s objective and the
        # multi-window burn rate must flip
        time.sleep(1.1)  # past the short window: the chaos traffic is
        # the only thing the 1 s window sees
        eng2 = GenerationEngine(StubBackend(2, 128, prefill_s=0.8),
                                prefill_chunk=8)
        for i in range(2):
            eng2.submit([50 + i, 2, 3], max_new_tokens=4)
        eng2.run_until_idle()
        chaos = telemetry.snapshot().get("slo") or {}
        chaos_ttft = chaos.get("objectives", {}).get("ttft", {})
        burn_gauge = telemetry.registry().snapshot()["gauges"] \
            .get("slo_ttft_burn_rate") or {}
        breach_event = any(e.get("name") == "slo_breach"
                           for e in events.get_recorder().tail())
        telemetry.stop()
        telemetry.reset()
        slo.reset()
        events.reset()  # close the stream so the report reads full books

        report = {}
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "request_report.py"),
             event_dir, "--json"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                if line.startswith("{"):
                    report = json.loads(line)
                    break
        slowest = (report.get("slowest") or [{}])[0]
        live_states = {s.get("state")
                       for s in (live or {}).get("slots", [])}
        healthy_compliance = healthy_slo.get("compliance")
        return {
            "serving_endpoint_live_mid_run": live is not None,
            "live_slots_busy": (live or {}).get("slots_busy"),
            "live_queue_depth": ((live or {}).get("queue") or {})
            .get("depth"),
            "live_slot_states": sorted(s for s in live_states if s),
            "healthy_ttft_compliance": healthy_compliance,
            "chaos_breaching": chaos_ttft.get("breaching"),
            "chaos_burn_rate": chaos_ttft.get("burn_rate"),
            "burn_gauge_value": burn_gauge.get("value"),
            "slo_breach_event": breach_event,
            "report_rc": proc.returncode,
            "report_completed": report.get("completed"),
            "slowest_dominant_phase": slowest.get("dominant_phase"),
            "max_unattributed_frac":
                report.get("max_unattributed_frac"),
            "ok": live is not None
            and bool(live_states & {"running", "prefilling"})
            and healthy_compliance is not None
            and healthy_compliance >= 0.99
            and chaos_ttft.get("breaching") is True
            and (burn_gauge.get("value") or 0) > 1.0
            and breach_event
            and proc.returncode == 0
            and report.get("completed") == 26
            # the chaos requests are the slowest and their wall is the
            # injected 0.8 s prefill sleep — the report must name the
            # prefill side (the later of the two spends its wall
            # WAITING for the other's chunk: same cause, "prefill_wait")
            and slowest.get("dominant_phase") in ("prefill",
                                                  "prefill_wait")
            and (report.get("max_unattributed_frac") or 1.0) <= 0.05,
        }
    finally:
        for v in ("SPARKDL_EVENT_DIR", "SPARKDL_SLO_TTFT_S",
                  "SPARKDL_SLO_LATENCY_S", "SPARKDL_SLO_WINDOWS_S",
                  "SPARKDL_METRICS_INTERVAL_S"):
            os.environ.pop(v, None)


_TRACE_WORKER = """
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
from sparkdl_tpu.runner import events, metrics

for i in range(3):
    with events.span("train_step", step=i):
        time.sleep(0.01)
    metrics.touch_heartbeat(i)
events.reset()  # close the stream cleanly
"""


def _trace_leg(out_dir: str) -> dict:
    """ISSUE 17: causal trace, end-to-end. A supervised 2-rank gang runs
    under a supervisor-minted trace id; afterwards a stub-backend engine
    serves requests IN THIS PROCESS under the same trace (env-adopted
    parent = the run root). ``trace_export.py`` must then merge both
    ranks' streams + the serving spans + telemetry gauge histories into
    one valid Chrome trace: every span carries the one trace_id and a
    parent chain resolving to the run root, >= 2 rank pids, >= 1 request
    track, counter tracks present, clock skew annotated."""
    import subprocess
    import time

    event_dir = os.path.join(out_dir, "trace_events")
    hb_dir = os.path.join(out_dir, "trace_hb")
    metrics_dir = os.path.join(out_dir, "trace_metrics")
    worker = os.path.join(out_dir, "trace_worker.py")
    with open(worker, "w") as f:
        f.write(_TRACE_WORKER.format(repo=_REPO))

    supervise(worker, np=2, timeout_s=300.0, max_restarts=0,
              backoff_s=0.1, poll_s=0.25, event_dir=event_dir,
              heartbeat_dir=hb_dir)

    from sparkdl_tpu.runner import events, telemetry, traceview
    manifest = traceview.find_trace_manifest(event_dir) or {}
    os.environ["SPARKDL_EVENT_DIR"] = event_dir
    os.environ[events.TRACE_ID_ENV] = manifest.get("trace_id") or ""
    os.environ[events.TRACE_PARENT_ENV] = \
        manifest.get("root_span_id") or ""
    os.environ["SPARKDL_METRICS_INTERVAL_S"] = "0.05"
    try:
        from sparkdl_tpu.serving import GenerationEngine, StubBackend

        events.reset()  # re-arm on the gang's dir, now traced
        telemetry.reset()
        telemetry.start(metrics_dir=metrics_dir)
        eng = GenerationEngine(StubBackend(2, 128, step_s=0.002),
                               prefill_chunk=8)
        for i in range(3):
            eng.submit([1 + i, 2, 3], max_new_tokens=8)
        eng.run_until_idle()
        time.sleep(0.12)  # one exporter tick -> a history line on disk
        telemetry.stop()
        telemetry.reset()
        events.reset()  # close the stream so the export reads full books
    finally:
        for v in ("SPARKDL_EVENT_DIR", events.TRACE_ID_ENV,
                  events.TRACE_PARENT_ENV, "SPARKDL_METRICS_INTERVAL_S"):
            os.environ.pop(v, None)

    summary = {}
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "trace_export.py"), event_dir,
         "--metrics-dir", metrics_dir, "--heartbeat-dir", hb_dir,
         "--validate", "--require-ranks", "2", "--require-requests",
         "1", "--require-counters"],
        capture_output=True, text=True, timeout=120)
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            summary = json.loads(line)
            break
    verdict = summary.get("validation") or {}
    skew = summary.get("clock_skew") or {}
    return {
        "export_rc": proc.returncode,
        "trace_id": summary.get("trace_id"),
        "spans": summary.get("spans"),
        "requests": summary.get("requests"),
        "ranks": verdict.get("ranks"),
        "traced_spans": verdict.get("traced_spans"),
        "counters": verdict.get("counters"),
        "skew_measured": skew.get("measured"),
        "problems": verdict.get("problems"),
        "ok": proc.returncode == 0
        and verdict.get("ok") is True
        and summary.get("trace_id") == manifest.get("trace_id")
        and bool(manifest.get("trace_id"))
        and (verdict.get("traced_spans") or 0) > 0
        and skew.get("measured") is True,
    }


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="sparkdl-obs-smoke-")
    event_dir = os.path.join(out_dir, "events")
    worker = os.path.join(out_dir, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER.format(repo=_REPO))

    plan = FaultPlan([Fault("step_start", "preempt", at_step=3)])
    err = None
    try:
        supervise(worker, np=1, args=[out_dir], timeout_s=300.0,
                  max_restarts=0, backoff_s=0.1, poll_s=0.25, plan=plan,
                  event_dir=event_dir)
    except GangFailure as e:
        err = e

    tl = err.timeline if err is not None else None
    merged_path = os.path.join(event_dir, GANG_TIMELINE_FILE)
    on_disk = {}
    if os.path.exists(merged_path):
        with open(merged_path) as f:
            on_disk = json.load(f)
    ff = (tl or {}).get("first_failure") or {}
    postmortem_ok = (err is not None
                     and tl is not None
                     and tl.get("first_failing_rank") == 0
                     and ff.get("site") == "step_start"
                     and ff.get("step") == 3
                     and (tl["ranks"].get("0") or {}).get("last_step") == 3
                     and on_disk.get("first_failing_rank") == 0
                     and "UNAVAILABLE" in str(err))
    telemetry = _scoring_leg(out_dir)
    ingest = _ingest_leg(out_dir)
    serving = _serving_leg(out_dir)
    trace = _trace_leg(out_dir)
    ok = postmortem_ok and telemetry["ok"] and ingest["ok"] \
        and serving["ok"] and trace["ok"]
    print(json.dumps({
        "ok": ok,
        "postmortem_ok": postmortem_ok,
        "first_failing_rank": tl.get("first_failing_rank") if tl else None,
        "fault_site": ff.get("site"),
        "fault_step": ff.get("step"),
        "last_step": (tl["ranks"].get("0") or {}).get("last_step")
        if tl else None,
        "gang_timeline": merged_path,
        "telemetry": telemetry,
        "ingest": ingest,
        "serving": serving,
        "trace": trace,
        "out_dir": out_dir,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
