#!/usr/bin/env python
"""Observability smoke: flight recorder + gang-timeline postmortem,
end-to-end through the supervising launcher, on CPU (ISSUE 2 satellite).

Flow: ``supervise(max_restarts=0)`` launches a single-rank training worker
with the flight recorder armed (``SPARKDL_EVENT_DIR`` is injected by the
supervisor) and a ``FaultPlan`` that raises an UNAVAILABLE-shaped preemption
at step 3. The worker dies; ``fit()``'s failure path flushes a crash
postmortem; the supervisor merges the rank's event stream, postmortem, and
heartbeat into ``gang_timeline.json`` and raises a :class:`GangFailure`
carrying it. This script asserts the merged postmortem names the faulted
rank, its last step, and the chaos site, then prints one JSON line and
exits 0.

Run: ``JAX_PLATFORMS=cpu python scripts/obs_smoke.py``
"""

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The supervisor never queries devices, so no jax backend is initialized
# in this process — the workers own the chips.
from sparkdl_tpu.runner.chaos import Fault, FaultPlan  # noqa: E402
from sparkdl_tpu.runner.events import GANG_TIMELINE_FILE  # noqa: E402
from sparkdl_tpu.runner.launcher import GangFailure, supervise  # noqa: E402

_WORKER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import optax
from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

out_dir = sys.argv[1]
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
rng = np.random.RandomState(0)
params = {{"w": rng.randn(4, 3).astype(np.float32)}}

def data():
    r = np.random.RandomState(1)
    while True:
        yield {{"image": r.randn(8, 4).astype(np.float32),
               "label": r.randint(0, 3, (8,))}}

runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"], data=data(), num_steps=6,
    checkpoint_every=2, log_every=100))
"""


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="sparkdl-obs-smoke-")
    event_dir = os.path.join(out_dir, "events")
    worker = os.path.join(out_dir, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER.format(repo=_REPO))

    plan = FaultPlan([Fault("step_start", "preempt", at_step=3)])
    err = None
    try:
        supervise(worker, np=1, args=[out_dir], timeout_s=300.0,
                  max_restarts=0, backoff_s=0.1, poll_s=0.25, plan=plan,
                  event_dir=event_dir)
    except GangFailure as e:
        err = e

    tl = err.timeline if err is not None else None
    merged_path = os.path.join(event_dir, GANG_TIMELINE_FILE)
    on_disk = {}
    if os.path.exists(merged_path):
        with open(merged_path) as f:
            on_disk = json.load(f)
    ff = (tl or {}).get("first_failure") or {}
    ok = (err is not None
          and tl is not None
          and tl.get("first_failing_rank") == 0
          and ff.get("site") == "step_start"
          and ff.get("step") == 3
          and (tl["ranks"].get("0") or {}).get("last_step") == 3
          and on_disk.get("first_failing_rank") == 0
          and "UNAVAILABLE" in str(err))
    print(json.dumps({
        "ok": ok,
        "first_failing_rank": tl.get("first_failing_rank") if tl else None,
        "fault_site": ff.get("site"),
        "fault_step": ff.get("step"),
        "last_step": (tl["ranks"].get("0") or {}).get("last_step")
        if tl else None,
        "gang_timeline": merged_path,
        "out_dir": out_dir,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
