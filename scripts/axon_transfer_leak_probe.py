"""Minimal repro: the axon PJRT client leaks host RSS on every
host->device transfer (round-5 finding, 2026-07-31).

A bare device_put -> jitted compute -> del loop, with every Python
reference dropped and the result blocked, grows host RSS by exactly the
transfer payload per iteration (measured 32.7 MB/iter for a 34 MB
batch; same growth via implicit jit-argument transfer and with
donate_argnums). The framework's own data plane is O(batch): the same
streaming path holds RSS flat on the CPU backend
(tests/test_bench.py::test_northstar_leg_streams_in_o_batch_memory),
so sustained-throughput RSS growth on axon (e.g. the bench north-star
leg's ~490 KB/row) is client staging, not framework residency.

Run: python scripts/axon_transfer_leak_probe.py  (needs the axon TPU)
"""

import numpy as np


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        return int(f.read().split("VmRSS:")[1].split()[0]) / 1024


def main():
    import jax
    import jax.numpy as jnp

    x = np.random.randint(0, 255, size=(128, 299, 299, 3), dtype=np.uint8)
    payload_mb = x.nbytes / 1e6
    f = jax.jit(lambda a: (a.astype(jnp.float32) / 255.0).sum(axis=(1, 2, 3)))
    jax.block_until_ready(f(jax.device_put(x)))  # compile + first transfer
    r0 = rss_mb()
    iters = 30
    for _ in range(iters):
        d = jax.device_put(x)
        o = f(d)
        jax.block_until_ready(o)
        del d, o
    delta = rss_mb() - r0
    print(f"payload {payload_mb:.1f} MB x {iters} transfers -> "
          f"RSS delta {delta:.0f} MB ({delta / iters:.1f} MB/transfer)")
    if delta > 0.5 * payload_mb * iters:
        print("LEAK: client retains ~every transfer buffer")


if __name__ == "__main__":
    main()
