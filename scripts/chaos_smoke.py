#!/usr/bin/env python
"""Chaos smoke: one injected preemption + checkpoint resume, end-to-end
through the supervising launcher, on CPU (ISSUE 1 satellite).

Flow: ``supervise()`` launches a single-rank training worker with a
``FaultPlan`` that raises an UNAVAILABLE-shaped preemption at step 3 (env
transport — the worker script has zero chaos awareness). Attempt 1
checkpoints at step 2 and dies; the supervisor classifies the stderr
retryable and relaunches; attempt 2 resumes from the checkpoint and runs
only the remaining steps. Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/chaos_smoke.py``
"""

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The supervisor never queries devices, so no jax backend is initialized
# in this process — the workers own the chips.
from sparkdl_tpu.runner.chaos import Fault, FaultPlan  # noqa: E402
from sparkdl_tpu.runner.launcher import supervise  # noqa: E402

_WORKER = """
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import optax
from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

out_dir = sys.argv[1]
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
rng = np.random.RandomState(0)
params = {{"w": rng.randn(4, 3).astype(np.float32)}}

def data():
    r = np.random.RandomState(1)
    while True:
        yield {{"image": r.randn(8, 4).astype(np.float32),
               "label": r.randint(0, 3, (8,))}}

res = runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"], data=data(), num_steps=6,
    checkpoint_every=2, log_every=100))
with open(os.path.join(out_dir, "attempts.jsonl"), "a") as f:
    f.write(json.dumps({{"final_step": int(res["state"].step),
                        "steps_this_attempt": res["meter"].steps}}) + "\\n")
"""


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="sparkdl-chaos-smoke-")
    worker = os.path.join(out_dir, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER.format(repo=_REPO))

    plan = FaultPlan([Fault("step_start", "preempt", at_step=3)])
    res = supervise(worker, np=1, args=[out_dir], timeout_s=300.0,
                    max_restarts=2, backoff_s=0.1, poll_s=0.25, plan=plan)

    attempts_path = os.path.join(out_dir, "attempts.jsonl")
    attempts = [json.loads(ln) for ln in open(attempts_path)]
    # Only the surviving attempt writes: it must have finished at step 6
    # having run just the 4 post-checkpoint steps (resume from step 2).
    ok = (res.restarts == 1
          and res.failure_kinds == ["retryable"]
          and len(attempts) == 1
          and attempts[0]["final_step"] == 6
          and attempts[0]["steps_this_attempt"] == 4)
    print(json.dumps({
        "ok": ok,
        "restarts": res.restarts,
        "failure_kinds": res.failure_kinds,
        "final_step": attempts[0]["final_step"] if attempts else None,
        "steps_in_resumed_attempt":
            attempts[0]["steps_this_attempt"] if attempts else None,
        "resumed_from_step": 2,
        "out_dir": out_dir,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
