"""Characterize the flash-bench timing anomaly on the axon TPU.

BENCH_TPU_MEASURED2's flash leg shows the first two timings of the leg at
~0.04 ms and every later timing (any kernel, any seq) at ~13 ms — a pattern
that tracks *position in the run*, not the computation.  This probe times
dense and flash attention at S=512/1024 three ways to separate real kernel
time from dispatch/tunnel artifacts:

  amortized  - dispatch N calls back-to-back, block once at the end
               (the bench harness's method)
  percall    - block_until_ready after every call
  chained    - feed each output back in as the next q, forcing a data
               dependency so the device can't overlap queue slots

Run: timeout 600 python scripts/flash_timing_probe.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.ops.flash_attention import flash_attention
from sparkdl_tpu.parallel.ring_attention import dense_attention
from sparkdl_tpu.utils.platform import is_tpu_backend

REPS = 20


def amortized(fn, *args):
    o = fn(*args)
    jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(REPS):
        o = fn(*args)
    jax.block_until_ready(o)
    return (time.perf_counter() - t0) / REPS


def percall(fn, *args):
    o = fn(*args)
    jax.block_until_ready(o)
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts), sum(ts) / len(ts)


def chained(fn, q, k, v):
    o = fn(q, k, v)
    jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(REPS):
        o = fn(o, k, v)
    jax.block_until_ready(o)
    return (time.perf_counter() - t0) / REPS


def main():
    compiled = is_tpu_backend()
    print("backend", jax.devices()[0].platform, "compiled", compiled, flush=True)
    for s in (512, 1024):
        rng = np.random.RandomState(s)
        q, k, v = [jnp.asarray(rng.randn(2, 8, s, 64).astype(np.float32) * .3)
                   for _ in range(3)]
        flash = jax.jit(lambda a, b, c: flash_attention(
            a, b, c, causal=True, interpret=not compiled))
        dense = jax.jit(lambda a, b, c: dense_attention(a, b, c, True))
        for name, fn in (("dense", dense), ("flash", flash)):
            am = amortized(fn, q, k, v)
            pc_min, pc_mean = percall(fn, q, k, v)
            ch = chained(fn, q, k, v)
            print(f"S={s} {name}: amortized {am*1e3:.3f}ms  "
                  f"percall min {pc_min*1e3:.3f} mean {pc_mean*1e3:.3f}ms  "
                  f"chained {ch*1e3:.3f}ms", flush=True)
        # Re-time the FIRST kernel again at the END: if position in the
        # run (not the kernel) sets the time, this re-run shows it.
        am2 = amortized(dense, q, k, v)
        print(f"S={s} dense re-timed at end: amortized {am2*1e3:.3f}ms",
              flush=True)


if __name__ == "__main__":
    main()
