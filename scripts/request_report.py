#!/usr/bin/env python
"""Tail-latency explainer over flight-recorder span streams (ISSUE 13).

``bottleneck_report.py`` answers "which *stage* is the bottleneck";
this report answers "why was request X slow": it re-assembles the
serving engine's per-request ``serve_*`` spans (the SAME fold the live
``telemetry.RequestTraceCollector`` runs — they cannot drift) from an
event dir into one trace per request, prints exact latency/TTFT
percentiles, the slowest-N requests with full phase attribution
(queue / prefill / prefill-wait / block-stall / draft / decode /
unattributed), and names the **dominant cause of the p99 tail**. With
``SPARKDL_SLO_*`` objectives armed it appends a whole-stream SLO
compliance block (exact per-trace values — the offline twin of the
live burn-rate monitor).

Usage:
    python scripts/request_report.py EVENT_DIR [--top N] [--json]

Exit codes: 0 = report printed; 2 = no serve_* trace evidence found.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# analysis/telemetry/slo are stdlib-only; the package import pulls jax
# into the interpreter (inert — no device query, so no backend init:
# the same rule bottleneck_report rides).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from sparkdl_tpu.runner import analysis  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-request phase attribution + tail-latency "
                    "explanation from flight-recorder span streams")
    ap.add_argument("event_dir",
                    help="directory of events_rank*.jsonl streams "
                         "(SPARKDL_EVENT_DIR; gang-*/ subdirs included)")
    ap.add_argument("--top", type=int, default=8,
                    help="how many slowest requests to tabulate "
                         "(default 8)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object instead "
                         "of the table")
    ns = ap.parse_args(argv)

    recs = analysis.load_event_dir(ns.event_dir)
    req = analysis.request_summary(recs, top_n=max(1, ns.top))
    if req is None:
        print(f"request_report: no completed serve_* request traces "
              f"under {ns.event_dir}", file=sys.stderr)
        return 2
    if ns.json:
        print(json.dumps(req, default=str))
    else:
        print(analysis.format_request_summary(req))
    return 0


if __name__ == "__main__":
    sys.exit(main())
