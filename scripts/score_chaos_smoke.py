#!/usr/bin/env python
"""Scoring chaos smoke: injected decode faults + an injected dispatch fault
through the fault-tolerant streaming scorer, on CPU (ISSUE 4).

Three passes over one synthetic image frame (13 partitions, one emptied
mid-stream by a filter):

1. **Clean run** — no chaos; per-origin feature vectors are the ground
   truth.
2. **Decode-fault run** — a seeded ``decode``-site fault plan fails a
   fraction of chunk/row decodes; ``onError='quarantine'`` must complete
   the job, dead-letter exactly the failing rows (error_class =
   ``InjectedFatal``), and score every surviving row **bit-identically**
   to the clean run. Quarantine counts must agree across the dead-letter
   sink, input-minus-output, and ``run_stats.rows_quarantined``.
3. **Dispatch-retry run** — a once-only ``dispatch`` preemption; the
   bounded retry must absorb it (job completes, all rows scored, a
   ``retry`` flight-recorder event on record).

Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/score_chaos_smoke.py``
"""

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SPARKDL_DISPATCH_BACKOFF_S", "0.05")

ROWS = int(os.environ.get("SCORE_CHAOS_ROWS", "104"))
BATCH = int(os.environ.get("SCORE_CHAOS_BATCH", "8"))
PARTS = int(os.environ.get("SCORE_CHAOS_PARTS", "13"))
DECODE_FAULT_PROB = float(os.environ.get("SCORE_CHAOS_PROB", "0.25"))


def main() -> int:
    import numpy as np
    import pyarrow as pa

    import sparkdl_tpu as sdl
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.runner import chaos, events, metrics
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan

    rng = np.random.RandomState(0)
    structs = [imageIO.imageArrayToStruct(
        rng.randint(0, 256, size=(12, 12, 3)).astype(np.uint8),
        origin=f"img_{i}") for i in range(ROWS)]
    df_full = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=PARTS)
    # Empty one partition mid-stream: rows of partition 6 are filtered
    # out, so the engine must carry an empty partition without desyncing
    # partition reassembly (the acceptance's "incl. empty partitions").
    per = -(-ROWS // PARTS)
    dropped = set(range(6 * per, 7 * per))
    df = df_full.filter(
        lambda r: int(r.image["origin"].split("_")[1]) not in dropped)
    expected_origins = [f"img_{i}" for i in range(ROWS) if i not in dropped]

    def scorer(on_error):
        return sdl.XlaImageTransformer(
            inputCol="image", outputCol="features",
            fn=lambda b: b.mean(axis=(1, 2)), inputSize=(8, 8),
            batchSize=BATCH, onError=on_error)

    def score(t):
        rows = t.transform(df).collect()
        return {r.image["origin"]: np.asarray(r.features, np.float32)
                for r in rows}

    # -- 1. clean ground truth --------------------------------------------
    chaos.uninstall()
    metrics.run_stats.reset()
    clean = score(scorer("raise"))
    assert len(clean) == len(expected_origins), \
        f"clean run scored {len(clean)}/{len(expected_origins)}"

    # -- 2. injected decode faults + quarantine ---------------------------
    metrics.run_stats.reset()
    events.reset(ring_size=8192)
    chaos.install(FaultPlan(
        [Fault("decode", "fatal", prob=DECODE_FAULT_PROB, once=False)],
        seed=7))
    t = scorer("quarantine")
    try:
        faulted = score(t)
    finally:
        chaos.uninstall()
    dead = t.deadLetters()
    quarantined = dead.num_rows
    scored = len(faulted)
    survivors_identical = all(
        np.array_equal(clean[o], faulted[o]) for o in faulted)
    counts_agree = (
        scored + quarantined == len(expected_origins)
        and quarantined == metrics.run_stats.rows_quarantined)
    classes = set(dead.column("error_class").to_pylist())
    dead_letter_ok = (quarantined > 0 and classes == {"InjectedFatal"}
                      and dead.column_names[-2:] == ["error_class", "error"])

    # -- 3. transient dispatch fault absorbed by the bounded retry --------
    metrics.run_stats.reset()
    rec = events.reset(ring_size=8192)
    chaos.install(FaultPlan(
        [Fault("dispatch", "preempt", prob=1.0, once=True)], seed=11))
    try:
        retried = score(scorer("raise"))
    finally:
        chaos.uninstall()
    retry_events = [e for e in rec.tail() if e["name"] == "retry"]
    retry_ok = (len(retried) == len(expected_origins)
                and len(retry_events) >= 1
                and metrics.run_stats.dispatch_retries >= 1)

    ok = (survivors_identical and counts_agree and dead_letter_ok
          and retry_ok)
    print(json.dumps({
        "ok": ok,
        "rows": len(expected_origins),
        "scored": scored,
        "quarantined": quarantined,
        "quarantine_counts_agree": counts_agree,
        "survivors_bit_identical": survivors_identical,
        "dead_letter_classes": sorted(classes),
        "dispatch_retry_events": len(retry_events),
        "dispatch_retry_ok": retry_ok,
        "fault_tolerance": metrics.fault_tolerance_summary(),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
