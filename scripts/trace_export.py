#!/usr/bin/env python
"""Export a merged Chrome-trace timeline from observability artifacts
(ISSUE 17).

One command turns a run's scattered evidence — per-rank
``events_rank*.jsonl`` span streams (``gang-*/`` subdirs included), the
supervisor's ``trace_manifest.json`` span tree, telemetry snapshot
histories (gauge/counter tracks), and PR 13 request traces — into ONE
Chrome trace-event JSON loadable in Perfetto (https://ui.perfetto.dev)
or ``chrome://tracing``. Cross-rank clock skew is measured from
heartbeat bodies when a heartbeat dir is given, and annotated in
``otherData.clock_skew`` either way — unmeasured skew says so
explicitly, it never silently reads as zero.

Usage:
    python scripts/trace_export.py EVENT_DIR [--metrics-dir DIR]
        [--heartbeat-dir DIR] [--out FILE] [--validate]
        [--require-ranks N] [--require-requests N] [--require-counters]

Prints one JSON summary line (path, event counts, validation verdict).
Exit codes: 0 = exported (and validated, if asked); 1 = validation
failed; 2 = no events found under EVENT_DIR.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# traceview/analysis/telemetry are stdlib-only; the package import pulls
# jax into the interpreter (inert — no device query, so no backend
# init: the same rule request_report rides).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from sparkdl_tpu.runner import traceview  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge flight-recorder streams, telemetry histories "
                    "and request traces into one Perfetto-loadable "
                    "Chrome trace")
    ap.add_argument("event_dir",
                    help="directory of events_rank*.jsonl streams "
                         "(SPARKDL_EVENT_DIR; gang-*/ subdirs included)")
    ap.add_argument("--metrics-dir", default=None,
                    help="SPARKDL_METRICS_DIR with metrics_rank*.jsonl "
                         "histories -> counter tracks")
    ap.add_argument("--heartbeat-dir", default=None,
                    help="SPARKDL_HEARTBEAT_DIR with rank*.hb beats -> "
                         "per-rank clock-skew annotation")
    ap.add_argument("--out", default=None,
                    help="output path (default EVENT_DIR/trace.json)")
    ap.add_argument("--validate", action="store_true",
                    help="run structural validation and fail (exit 1) "
                         "on problems")
    ap.add_argument("--require-ranks", type=int, default=1,
                    help="--validate: spans must cover >= N ranks "
                         "(default 1)")
    ap.add_argument("--require-requests", type=int, default=0,
                    help="--validate: >= N request tracks (default 0)")
    ap.add_argument("--require-counters", action="store_true",
                    help="--validate: demand gauge/counter tracks")
    ns = ap.parse_args(argv)

    trace = traceview.chrome_trace(ns.event_dir,
                                   metrics_dir=ns.metrics_dir,
                                   heartbeat_dir=ns.heartbeat_dir)
    other = trace["otherData"]
    if not other["spans"] and not other["instants"]:
        print(f"trace_export: no events under {ns.event_dir}",
              file=sys.stderr)
        return 2
    out_path = ns.out or os.path.join(ns.event_dir, "trace.json")
    traceview.write_chrome_trace(out_path, trace)

    summary = {"out": os.path.abspath(out_path),
               "trace_id": other["trace_id"],
               "events": len(trace["traceEvents"]),
               "spans": other["spans"], "instants": other["instants"],
               "requests": other["requests"],
               "clock_skew": other["clock_skew"]}
    rc = 0
    if ns.validate:
        verdict = traceview.validate_chrome_trace(
            trace, require_ranks=ns.require_ranks,
            require_requests=ns.require_requests,
            require_counters=ns.require_counters)
        summary["validation"] = verdict
        rc = 0 if verdict["ok"] else 1
    print(json.dumps(summary, default=str))
    return rc


if __name__ == "__main__":
    sys.exit(main())
