#!/usr/bin/env python
"""Scoring smoke: the streaming inference engine end-to-end on CPU
(ISSUE 3 satellite, next to ``chaos_smoke``/``obs_smoke``).

Two CHILD scoring processes share one ``SPARKDL_COMPILE_CACHE`` dir. Each
scores a synthetic image frame through ``XlaImageTransformer`` — parallel
host decode, one continuous cross-partition device stream, overlap-worker
Arrow encode — and prints examples/s plus the per-stage time breakdown
aggregated from the flight-recorder event stream. The parent asserts:

- every scoring stage (decode/pad/put/dispatch/fetch/encode) emitted spans;
- the FIRST process paid persistent compilation-cache misses;
- the SECOND process logged compilation-cache HITS — a gang restart or
  repeat scoring job skips the recompile instead of paying it again.

Run: ``JAX_PLATFORMS=cpu python scripts/score_smoke.py``
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = int(os.environ.get("SCORE_SMOKE_ROWS", "96"))
BATCH = int(os.environ.get("SCORE_SMOKE_BATCH", "16"))
PARTS = int(os.environ.get("SCORE_SMOKE_PARTS", "12"))


def child() -> int:
    """One scoring process: synthetic frame → streaming engine → JSON."""
    import numpy as np
    import pyarrow as pa

    import sparkdl_tpu as sdl
    from sparkdl_tpu.core import runtime
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.runner import events

    rec = events.reset(ring_size=8192)  # hold every span of the run
    rng = np.random.RandomState(0)
    structs = [imageIO.imageArrayToStruct(
        rng.randint(0, 256, size=(24, 24, 3)).astype(np.uint8),
        origin=f"synthetic_{i}") for i in range(ROWS)]
    df = sdl.DataFrame.fromArrow(
        pa.table({"image": pa.array(structs, type=imageIO.imageSchema)}),
        numPartitions=PARTS)

    t = sdl.XlaImageTransformer(
        inputCol="image", outputCol="features",
        fn=lambda b: b.mean(axis=(1, 2)),
        inputSize=(16, 16), batchSize=BATCH)
    t0 = time.perf_counter()
    rows = t.transform(df).collect()
    wall = time.perf_counter() - t0
    assert len(rows) == ROWS, f"scored {len(rows)} of {ROWS} rows"

    stages: dict = {}
    for e in rec.tail():
        if e.get("ph") == "E" and "dur_s" in e:
            stages[e["name"]] = round(
                stages.get(e["name"], 0.0) + e["dur_s"], 6)
    print(json.dumps({
        "rows": ROWS,
        "partitions": PARTS,
        "examples_per_sec": round(ROWS / wall, 2),
        "wall_s": round(wall, 4),
        "decode_workers": runtime.decode_workers_default(),
        "stages": stages,
        "compile_cache": runtime.persistent_cache_stats(),
    }))
    return 0


def main() -> int:
    cache_dir = tempfile.mkdtemp(prefix="sparkdl-score-cache-")
    env = dict(os.environ)
    env["SPARKDL_COMPILE_CACHE"] = cache_dir

    def run_child() -> dict:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            capture_output=True, text=True, env=env, timeout=300)
        if proc.returncode != 0:
            print(proc.stdout, end="")
            print(proc.stderr, end="", file=sys.stderr)
            raise RuntimeError(f"scoring child exited {proc.returncode}")
        line = [ln for ln in proc.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        return json.loads(line)

    first = run_child()
    second = run_child()

    stage_names = {"decode", "pad", "put", "dispatch", "fetch", "encode"}
    ok = (stage_names <= set(first["stages"])
          and first["compile_cache"]["misses"] > 0
          # the second process loads the SAME programs from the shared
          # on-disk cache — a hit logged instead of a recompile
          and second["compile_cache"]["hits"] > 0
          and second["rows"] == ROWS)

    print("per-stage breakdown (first run, seconds summed over spans):")
    for name in sorted(first["stages"], key=first["stages"].get,
                       reverse=True):
        print(f"  {name:10s} {first['stages'][name]:8.4f}")
    print(f"examples/s: first={first['examples_per_sec']} "
          f"second={second['examples_per_sec']}")
    print(f"compile cache: first={first['compile_cache']} "
          f"second={second['compile_cache']}")
    print(json.dumps({"ok": ok, "first_run": first, "second_run": second,
                      "cache_dir": cache_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
