#!/usr/bin/env python
"""Serving fleet chaos smoke (ISSUE 20 tentpole evidence).

Four backend shapes — Stub/Llama x unpaged/paged, all on CPU — each
driven as a ≥3-replica :class:`EngineFleet` through a concurrent
request mix that survives BOTH fleet failure modes in one run:

1. **Unclean replica death** — a ``replica_dead`` fault injected at the
   ``fleet_route`` chaos site kills the chosen replica with NO drain
   mid-stream; the router re-admits its in-flight requests from its own
   shadow state (prompt + fleet delivery cursor).
2. **DOOMED drain-and-re-admit** — a second replica is doomed while
   serving; its ``engine.drain()`` snapshots resume on the survivor.

The surviving output must be **token-identical to a clean
single-engine run** with **zero duplicated and zero lost streamed
tokens** (the delivery-cursor audit: ``streamed == request.tokens`` and
``delivered == len(tokens)`` for every request).

Fleet-policy legs (backend-independent, run on the stub):

3. **Min-replicas counterfactual** — with
   ``SPARKDL_FLEET_MIN_REPLICAS=2`` and one replica dead, the fleet
   fails CLOSED: ``submit`` raises one classified
   ``FleetDegradedError`` naming the knob; ``classify_exception`` and
   ``classify_text`` both call it retryable.
4. **Radix vs round-robin** — the same prefix-family workload through a
   radix-routed fleet and a round-robin fleet: the radix router must
   beat round-robin on fleet-wide prefix reuse (co-location keeps each
   family's head resident on ONE replica instead of re-prefilling it
   everywhere).

Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/fleet_chaos_smoke.py``
(``SERVE_CHAOS_SKIP_LLAMA=1`` limits to the stub shapes.)
"""

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

VOCAB = 997  # prime vocab: the stub's fold-chain stream is a real oracle
N_REPLICAS = 3


def _workload(rng, vocab: int, n: int, max_new=(6, 8, 10)):
    return [(rng.randint(1, vocab, size=int(rng.choice((2, 4, 7))))
             .tolist(), int(rng.choice(max_new))) for _ in range(n)]


def _clean_reference(make_engine, workload):
    """Ground truth: the whole workload on ONE uninterrupted engine."""
    eng = make_engine()
    reqs = [eng.submit(p, max_new_tokens=n, block=False)
            for p, n in workload]
    eng.run_until_idle()
    assert all(r.state == "done" for r in reqs), \
        [(r.id, r.state, str(r.error)[:80]) for r in reqs]
    return [list(r.tokens) for r in reqs]


def _audit_exactly_once(frs, streams):
    for fr in frs:
        if streams.get(fr.id, []) != fr.tokens:
            return False, (f"request {fr.id}: streamed "
                           f"{streams.get(fr.id)} != tokens {fr.tokens}")
        if fr.delivered != len(fr.tokens):
            return False, (f"request {fr.id}: delivered={fr.delivered} "
                           f"!= {len(fr.tokens)} tokens")
    return True, None


def fleet_survival_leg(name, make_engine, workload) -> dict:
    """Legs 1+2 for one backend shape: one unclean ``replica_dead``
    (chaos-injected at ``fleet_route``) plus one router-doomed
    drain-and-re-admit, under a concurrent mix, finishing
    token-identical to the clean single-engine reference."""
    from sparkdl_tpu.runner import chaos
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan
    from sparkdl_tpu.serving import DEAD, EngineFleet

    clean = _clean_reference(make_engine, workload)

    chaos.uninstall()
    fleet = EngineFleet([make_engine() for _ in range(N_REPLICAS)])
    streams = {}

    def cb(fr, tok):
        streams.setdefault(fr.id, []).append(tok)

    # the 4th routing decision's chosen replica dies UNCLEANLY — by
    # then the first three requests are mid-stream (stepped below), so
    # shadow re-admission must carry live delivery cursors
    chaos.install(FaultPlan([Fault("fleet_route", "replica_dead",
                                   at_step=4)]))
    try:
        frs = [fleet.submit(p, max_new_tokens=n, stream_cb=cb)
               for p, n in workload[:3]]
        for _ in range(3):
            fleet.step()
        assert any(fr.delivered for fr in frs), \
            f"[{name}] no tokens streamed before the injected death"
        frs += [fleet.submit(p, max_new_tokens=n, stream_cb=cb)
                for p, n in workload[3:]]
    finally:
        chaos.uninstall()
    deaths = fleet.stats["replica_deaths"]
    assert deaths == 1, f"[{name}] injected replica_dead did not fire"

    # now DOOM a second replica that is actively serving: drain + resume
    for _ in range(2):
        fleet.step()
    victim = next(fr.replica for fr in frs
                  if not fr.done and fr.replica is not None
                  and fleet.replica_state(fr.replica) != DEAD)
    fleet.doom_replica(victim, "smoke: doomed while serving")
    fleet.run_until_idle()

    assert all(fr.state == "done" for fr in frs), \
        f"[{name}] fleet run did not complete: " \
        f"{[(fr.id, fr.state, str(fr.error)[:80]) for fr in frs]}"
    identical = all(fr.tokens == c for fr, c in zip(frs, clean))
    assert identical, f"[{name}] not token-identical to the clean " + \
        f"single-engine run: " + str(
            [(fr.tokens, c) for fr, c in zip(frs, clean)
             if fr.tokens != c][:2])
    ok, why = _audit_exactly_once(frs, streams)
    assert ok, f"[{name}] exactly-once audit failed: {why}"
    assert fleet.stats["readmissions"] >= 1, fleet.stats
    assert fleet.stats["drains"] >= 1, fleet.stats
    hops = sum(fr.hops for fr in frs)
    assert hops >= 1, "no request actually hopped replicas"
    return {"requests": len(frs), "replica_deaths": deaths,
            "drains": fleet.stats["drains"],
            "readmissions": fleet.stats["readmissions"],
            "hops": hops, "token_identical": identical}


def min_replicas_counterfactual_leg() -> dict:
    """Leg 3: below the SPARKDL_FLEET_MIN_REPLICAS floor the fleet
    fails CLOSED with one classified error naming the knob."""
    from sparkdl_tpu.runner.failures import (classify_exception,
                                             classify_text)
    from sparkdl_tpu.serving import (EngineFleet, FleetDegradedError,
                                     GenerationEngine, StubBackend)

    os.environ["SPARKDL_FLEET_MIN_REPLICAS"] = "2"
    try:
        fleet = EngineFleet([
            GenerationEngine(StubBackend(2, 64, vocab_size=VOCAB))
            for _ in range(2)])
        assert fleet.min_replicas == 2  # the env knob armed it
        fleet.kill_replica(fleet.replica_names()[0])
        err = None
        try:
            fleet.submit([1, 2, 3], max_new_tokens=4)
        except FleetDegradedError as e:
            err = e
        assert err is not None, "sub-floor fleet accepted work"
        assert "SPARKDL_FLEET_MIN_REPLICAS" in str(err), err
        verdict = classify_exception(err)
        text_verdict = classify_text(f"FleetDegradedError: {err}")
        assert verdict == text_verdict == "retryable", \
            (verdict, text_verdict)
    finally:
        del os.environ["SPARKDL_FLEET_MIN_REPLICAS"]
    return {"error": type(err).__name__, "verdict": verdict,
            "fails_closed": True}


def radix_vs_round_robin_leg() -> dict:
    """Leg 4: fleet-wide prefix reuse, radix-aware router vs the
    round-robin comparator, on a prefix-family workload whose heads
    partition cleanly across the replicas."""
    import numpy as np

    from sparkdl_tpu.serving import (EngineFleet, GenerationEngine,
                                     StubBackend)

    rng = np.random.RandomState(7)
    families = [rng.randint(1, VOCAB, size=48).tolist() for _ in range(3)]
    workload = []
    # burst arrival (a session re-asking under one shared head): the
    # radix router keeps each family resident on ONE replica while
    # round-robin sprays the burst across all of them, re-prefilling
    # the same head everywhere
    for fi, head in enumerate(families):
        for i in range(8):
            workload.append((head + [500 + 10 * fi + i], 2))

    def run(routing):
        fleet = EngineFleet(
            [GenerationEngine(StubBackend(
                2, 96, vocab_size=VOCAB, prefix_cache_bytes=1 << 20))
             for _ in range(N_REPLICAS)], routing=routing)
        frs = [fleet.submit(p, max_new_tokens=n) for p, n in workload]
        fleet.run_until_idle()
        assert all(fr.state == "done" for fr in frs), routing
        reused = sum(getattr(fr._primary, "prefill_reused", 0) or 0
                     for fr in frs)
        prompt_tokens = sum(len(p) for p, _ in workload)
        return reused, round(reused / prompt_tokens, 4)

    radix_reused, radix_rate = run("radix")
    rr_reused, rr_rate = run("round_robin")
    assert radix_reused > rr_reused, \
        (f"radix router did not beat round-robin on fleet prefix "
         f"reuse: {radix_reused} <= {rr_reused}")
    return {"radix_reused_tokens": radix_reused,
            "radix_hit_rate": radix_rate,
            "round_robin_reused_tokens": rr_reused,
            "round_robin_hit_rate": rr_rate,
            "radix_beats_rr": True}


def main() -> int:
    import numpy as np

    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    rng = np.random.RandomState(0)
    out = {"legs": {}}

    stub_load = _workload(rng, VOCAB, 8)
    shapes = {
        "stub": lambda: GenerationEngine(
            StubBackend(2, 64, vocab_size=VOCAB), retries=1),
        "stub_paged": lambda: GenerationEngine(
            StubBackend(2, 64, vocab_size=VOCAB, block_size=8,
                        prefix_cache_bytes=1 << 20), retries=1),
    }
    if os.environ.get("SERVE_CHAOS_SKIP_LLAMA", "") != "1":
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        llama_load = _workload(rng, cfg.vocab_size, 4, max_new=(3, 5))

        def _llama(block_size=None):
            return GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=64,
                block_size=block_size, temperature=0.0, min_bucket=8,
                queue_capacity=64, retries=1)

        shapes["llama"] = lambda: _llama()
        shapes["llama_paged"] = lambda: _llama(block_size=16)

    for name, mk in shapes.items():
        load = stub_load if name.startswith("stub") else llama_load
        out["legs"][name] = fleet_survival_leg(name, mk, load)

    out["legs"]["min_replicas"] = min_replicas_counterfactual_leg()
    out["legs"]["radix_vs_rr"] = radix_vs_round_robin_leg()

    out["ok"] = (
        all(v.get("token_identical", True)
            for v in out["legs"].values())
        and out["legs"]["min_replicas"]["fails_closed"]
        and out["legs"]["radix_vs_rr"]["radix_beats_rr"])
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
