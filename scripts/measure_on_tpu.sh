#!/usr/bin/env bash
# One-command hardware measurement for when the axon TPU backend is up.
# Produces: bench JSON on stdout (+ BENCH_BASELINE.json on success) and
# the compiled-Pallas-kernel test record — the two pieces of evidence the
# round-3 verdict asked for (BASELINE M1/M2, SURVEY §5.7 compiled flash).
#
# Usage: bash scripts/measure_on_tpu.sh
# A hung backend costs BENCH_PROBE_TIMEOUT_S (default 180s), not the day.
set -u
cd "$(dirname "$0")/.."

# A full measurement window needs room: the all-legs bench (sweep with
# streamed/u8/lookahead twins, northstar 40k rows) far exceeds bench.py's
# driver-facing defaults (1200s wall / 480s per leg) — without these the
# resnet leg times out twice and the window records value 0.0.
export BENCH_WALL_S="${BENCH_WALL_S:-7200}"
export BENCH_TIMEOUT_S="${BENCH_TIMEOUT_S:-1800}"

echo "== 1/4 liveness probe ==" >&2
if ! timeout 120 python -c "import jax; print(jax.devices())" >&2; then
    echo "backend DOWN (probe hung/failed) — not measuring" >&2
    exit 1
fi

echo "== 2/4 bench (all legs, incl north-star scale + profile) ==" >&2
BENCH_NORTHSTAR_ROWS="${BENCH_NORTHSTAR_ROWS:-40000}" \
BENCH_PROFILE_DIR="${BENCH_PROFILE_DIR:-bench_profile}" \
BENCH_FLASH_SEQS="${BENCH_FLASH_SEQS:-512,1024,2048,4096}" \
BENCH_FLASH_BLOCKS="${BENCH_FLASH_BLOCKS:-128,256,512}" python bench.py
brc=$?
if [ $brc -ne 0 ]; then
    # The script's exit code gates probe_loop.sh's promote-the-record mv
    # AND its .probe_measured mark: a failed bench must fail the whole
    # script, or a later-passing pytest step would return rc=0 and a
    # partial record would be promoted over a good one — permanently,
    # since the mark also ends re-measurement for the round.
    echo "bench FAILED rc=$brc — not promoting a partial record" >&2
    exit $brc
fi

# bf16 flash pass (the in-model wire dtype) — separate artifact so the
# main stdout stays ONE parseable JSON record. Staged via tmp + mv for
# the same reason as the main record: a kill mid-leg must not truncate
# a previous window's good FLASH_BF16.json.
echo "== 3/4 bf16 flash kernel pass -> FLASH_BF16.json ==" >&2
if BENCH_FLASH_DTYPE=bfloat16 \
   BENCH_FLASH_SEQS="${BENCH_FLASH_SEQS:-512,1024,2048,4096}" \
   BENCH_FLASH_BLOCKS="${BENCH_FLASH_BLOCKS:-128,256,512}" \
       python bench.py --worker flash > FLASH_BF16.json.tmp; then
    mv FLASH_BF16.json.tmp FLASH_BF16.json
else
    echo "bf16 flash pass failed (non-fatal)" >&2
    rm -f FLASH_BF16.json.tmp
fi

# pytest output goes to stderr so stdout stays ONE parseable JSON record
# (probe_loop.sh captures stdout as $PROBE_MEASURED_OUT,
#  default BENCH_TPU_MEASURED.json)
echo "== 4/4 compiled Pallas kernel tests on the chip ==" >&2
SPARKDL_TEST_PLATFORM=axon python -m pytest tests/test_ops.py \
    tests/test_flash_decode.py -q >&2
