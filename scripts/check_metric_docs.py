#!/usr/bin/env python
"""Doc-drift lint: every metric name registered through
``telemetry.registry()`` must be documented in the README (ISSUE 13
satellite — the metrics twin of ``check_env_docs.py``).

PRs 6–12 grew ~25 counter/gauge/histogram names; each is one rename (or
one new metric) away from silently drifting out of the README's metrics
reference. This lint greps ``sparkdl_tpu/`` (plus ``bench.py`` and
``scripts/``) for registration call sites —
``.counter("name")`` / ``.gauge("name")`` / ``.histogram("name")`` and
the serving engine's ``_metric("kind", "name", ...)`` helper — and
fails loudly when any literal name is missing from ``README.md``.
(Names built dynamically escape the grep, same limitation as any
source lint; the codebase registers with literals for exactly this
reason.) Stdlib-only, no package import — it must run anywhere, fast,
as a tier-1 test and standalone in CI:

    python scripts/check_metric_docs.py      # exit 1 + list on drift
"""

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Registration call sites: reg.counter("x") / .gauge("x") /
# .histogram("x", ...) and the engine's _metric("gauge", "x", ...)
# indirection. Only literal first-argument names are caught.
_CALL_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*['\"]([A-Za-z_][A-Za-z0-9_]*)['\"]")
_HELPER_RE = re.compile(
    r"_metric\(\s*['\"](?:counter|gauge|histogram)['\"]\s*,\s*"
    r"['\"]([A-Za-z_][A-Za-z0-9_]*)['\"]")


def _py_files(root: str):
    roots = [os.path.join(root, "sparkdl_tpu"),
             os.path.join(root, "scripts"),
             os.path.join(root, "bench.py")]
    for top in roots:
        if os.path.isfile(top):
            yield top
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in filenames:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def code_metric_names(root: str = _REPO) -> set[str]:
    """Every metric name registered (with a literal) by package/bench/
    scripts code."""
    out: set[str] = set()
    for path in _py_files(root):
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError:
            continue
        out.update(_CALL_RE.findall(src))
        out.update(_HELPER_RE.findall(src))
    return out


def documented_metric_names(code_names: set[str],
                            readme: str | None = None) -> set[str]:
    """The subset of ``code_names`` that appear verbatim in the
    README."""
    readme = readme or os.path.join(_REPO, "README.md")
    try:
        with open(readme, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return set()
    return {n for n in code_names if n in text}


def missing_metrics(root: str = _REPO,
                    readme: str | None = None) -> list[str]:
    """Metric names registered in code but absent from the README,
    sorted."""
    code = code_metric_names(root)
    return sorted(code - documented_metric_names(code, readme))


def main() -> int:
    missing = missing_metrics()
    if missing:
        print("check_metric_docs: metric names registered through "
              "telemetry.registry() but missing from README.md:",
              file=sys.stderr)
        for n in missing:
            print(f"  {n}", file=sys.stderr)
        print("Document each in the README metrics reference "
              "(Live telemetry & bottleneck attribution section).",
              file=sys.stderr)
        return 1
    n = len(code_metric_names())
    print(f"check_metric_docs: ok — {n} metric names all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
