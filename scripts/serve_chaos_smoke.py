#!/usr/bin/env python
"""Serving survivability chaos smoke (ISSUE 19 tentpole evidence).

Four backend shapes — Stub/Llama x unpaged/paged, all on CPU — each
driven through:

1. **Clean run** — ground-truth greedy streams; every streamed token is
   ledgered through ``stream_cb``.
2. **Chaos run** — ``cache_lost`` injected at ``serve_decode`` AND
   ``serve_alloc`` (one fire each): the engine must fail over (host-side
   snapshot, backend rebuild, preemption-resume re-admission) and finish
   **token-identical** to the clean run with **zero duplicated or lost
   streamed tokens** — the delivery-cursor audit
   (``streamed == request.tokens`` and ``delivered == len(tokens)``).

Engine-layer legs (backend-independent semantics, run on the stub):

3. **Budget counterfactual** — ``cache_lost`` on EVERY prefill (no
   request ever progresses): the failover budget must exhaust, the
   engine fails CLOSED, and every pending request carries an
   ``EngineStopped`` naming ``SPARKDL_SERVE_FAILOVER_BUDGET``;
   ``classify_exception`` agrees it is retryable for the outer
   supervisor (a fresh engine can serve the same requests).
4. **Drain + resume** — ``drain()`` mid-run returns live snapshots that
   resume token-identically on a FRESH engine, nothing re-emitted.
5. **Quarantine ledger** — a poisoned prompt that loses the slot cache
   on every admission is quarantined individually while the rest of the
   fleet completes, and the count agrees across engine stats, telemetry
   counters, and the flight-recorder dead-letter events
   (``serve_request_quarantined``).

Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/serve_chaos_smoke.py``
(``SERVE_CHAOS_SKIP_LLAMA=1`` limits to the stub shapes.)
"""

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REQ = int(os.environ.get("SERVE_CHAOS_REQUESTS", "6"))
VOCAB = 997  # prime vocab: the stub's fold-chain stream is a real oracle


def _workload(rng, vocab: int, n: int, max_new=(4, 6, 8)):
    return [(rng.randint(1, vocab, size=int(rng.choice((2, 4, 7))))
             .tolist(), int(rng.choice(max_new))) for _ in range(n)]


def _run(make_engine, workload, plan=None):
    """One inline leg: submit everything with a stream ledger, drive to
    idle under ``plan`` (installed for the duration), return the engine
    and its requests + per-request streamed-token lists."""
    from sparkdl_tpu.runner import chaos

    chaos.uninstall()
    eng = make_engine()
    streams = {}

    def cb(req, tok):
        streams.setdefault(req.id, []).append(tok)

    if plan is not None:
        chaos.install(plan)
    try:
        reqs = [eng.submit(p, max_new_tokens=n, stream_cb=cb)
                for p, n in workload]
        eng.run_until_idle()
    finally:
        chaos.uninstall()
    return eng, reqs, streams


def _audit_exactly_once(reqs, streams):
    """The delivery-cursor audit: the streamed ledger must equal the
    final token list (no dup, no gap, in order) and the engine's cursor
    must sit at the emitted frontier."""
    for r in reqs:
        if streams.get(r.id, []) != r.tokens:
            return False, (f"request {r.id}: streamed "
                           f"{streams.get(r.id)} != tokens {r.tokens}")
        if r.delivered != len(r.tokens):
            return False, (f"request {r.id}: delivered={r.delivered} "
                           f"!= {len(r.tokens)} tokens")
    return True, None


def chaos_identity_leg(name, make_engine, workload) -> dict:
    """Legs 1+2 for one backend shape: clean ground truth, then the
    same workload under injected cache_lost at serve_decode +
    serve_alloc, asserting failover happened and was invisible in the
    output stream."""
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan

    clean_eng, clean, cstreams = _run(make_engine, workload)
    ok, why = _audit_exactly_once(clean, cstreams)
    assert ok, f"[{name}] clean-run stream ledger broken: {why}"
    assert all(r.state == "done" for r in clean), \
        f"[{name}] clean run did not complete"

    plan = FaultPlan([Fault("serve_decode", "cache_lost", prob=1.0),
                      Fault("serve_alloc", "cache_lost", prob=1.0)],
                     seed=3)
    eng, reqs, streams = _run(make_engine, workload, plan=plan)
    assert all(r.state == "done" for r in reqs), \
        f"[{name}] chaos run did not complete: " \
        f"{[(r.id, r.state, str(r.error)[:80]) for r in reqs]}"
    failovers = eng.stats["failovers"]
    assert failovers >= 1, f"[{name}] no failover fired"
    assert eng._failover_info["state"] == "recovered"
    identical = all(r.tokens == c.tokens for r, c in zip(reqs, clean))
    assert identical, f"[{name}] chaos run not token-identical: " + str(
        [(r.tokens, c.tokens) for r, c in zip(reqs, clean)
         if r.tokens != c.tokens][:2])
    ok, why = _audit_exactly_once(reqs, streams)
    assert ok, f"[{name}] exactly-once audit failed: {why}"
    return {"failovers": failovers,
            "resumed": eng.stats["failover_resumed"],
            "requests": len(reqs),
            "token_identical": identical}


def budget_counterfactual_leg() -> dict:
    """Leg 3: with cache_lost on every prefill nothing ever progresses,
    so the engine must exhaust its failover budget and fail CLOSED with
    a classified error — never loop forever."""
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan
    from sparkdl_tpu.runner.failures import classify_exception
    from sparkdl_tpu.serving import (EngineStopped, GenerationEngine,
                                     StubBackend)

    from sparkdl_tpu.runner import chaos

    budget = 2
    plan = FaultPlan([Fault("serve_prefill", "cache_lost", prob=1.0,
                            once=False)])
    chaos.uninstall()
    eng = GenerationEngine(StubBackend(2, 64, vocab_size=VOCAB),
                           retries=1, failover_budget=budget)
    chaos.install(plan)
    terminal = None
    try:
        reqs = [eng.submit(p, max_new_tokens=n)
                for p, n in [([5], 4), ([9], 4)]]
        try:
            eng.run_until_idle()
        except Exception as e:  # noqa: BLE001 — the fail-closed raise
            terminal = e
    finally:
        chaos.uninstall()
    # fail CLOSED means the driver sees the terminal error, not a hang
    assert terminal is not None, "engine kept stepping past the budget"
    assert eng._failover_info["state"] == "exhausted", eng._failover_info
    assert eng.stats["failovers"] == budget
    errs = [r.error for r in reqs]
    assert all(r.state == "failed" for r in reqs)
    assert all(isinstance(e, EngineStopped) for e in errs), errs
    assert all("failover budget exhausted" in str(e) for e in errs)
    assert all(f"SPARKDL_SERVE_FAILOVER_BUDGET={budget}" in str(e)
               for e in errs)
    verdicts = {classify_exception(e) for e in errs}
    assert verdicts == {"retryable"}, verdicts
    return {"budget": budget, "failovers": eng.stats["failovers"],
            "error_verdict": "retryable"}


def drain_resume_leg() -> dict:
    """Leg 4: drain a threaded engine mid-run; the snapshots must resume
    on a FRESH engine and finish token-identical to an uninterrupted
    run (greedy determinism + the exactly-once cursor)."""
    import time

    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    mk = lambda: GenerationEngine(  # noqa: E731
        StubBackend(2, 128, vocab_size=VOCAB, step_s=0.004), retries=1)
    workload = [([11 * (i + 1)], 12) for i in range(3)]
    _, clean, _ = _run(mk, workload)

    eng = mk().start()
    reqs = [eng.submit(p, max_new_tokens=n) for p, n in workload]
    deadline = time.time() + 10
    while time.time() < deadline and \
            not any(len(r.tokens) >= 4 for r in reqs):
        time.sleep(0.005)
    snaps = eng.drain(timeout=10)
    assert snaps, "drain() mid-run returned no live snapshots"
    fresh = mk()
    for r in snaps:
        fresh.resume(r)
    fresh.run_until_idle()
    assert all(r.state == "done" for r in reqs)
    identical = all(r.tokens == c.tokens for r, c in zip(reqs, clean))
    assert identical, [(r.tokens, c.tokens)
                       for r, c in zip(reqs, clean)]
    assert all(r.delivered == len(r.tokens) for r in reqs)
    return {"drained": len(snaps), "resumed_identical": identical}


def quarantine_ledger_leg() -> dict:
    """Leg 5: one poisoned prompt rides every failover without progress
    and is quarantined individually; the fleet completes, and the
    quarantine count agrees across engine stats, telemetry counters,
    and the flight-recorder dead-letter events."""
    from sparkdl_tpu.runner import events, telemetry
    from sparkdl_tpu.runner.chaos import InjectedCacheLost
    from sparkdl_tpu.serving import (GenerationEngine, RequestQuarantined,
                                     StubBackend)

    class PoisonStub(StubBackend):
        # dies at commit — AFTER co-resident chunk prefills emitted, so
        # the fleet progresses every cycle (engine streak resets) while
        # the poison request personally never gains a token
        def finish_prefill(self, slot, prompt, last_tok, aligned_len,
                           commit=True):
            if list(prompt)[:1] == [99]:
                raise InjectedCacheLost(
                    "poisoned request lost the slot cache")
            return super().finish_prefill(slot, prompt, last_tok,
                                          aligned_len, commit=commit)

    mk = lambda: GenerationEngine(  # noqa: E731
        PoisonStub(2, 64, vocab_size=VOCAB), retries=1,
        failover_budget=2, prefill_chunk=8, prefill_budget=16)
    # one short innocent (frees a slot so the poison admits) and one
    # LONG one that stays live across every poison failover — its
    # per-cycle progress is what keeps the engine streak at 1 while the
    # poison's personal count walks to the quarantine line
    good_load = [([7], 4), ([13], 30)]
    _, clean, _ = _run(mk, good_load)

    telemetry.reset()
    telemetry.start()
    rec = events.reset(ring_size=8192)
    try:
        eng, reqs, streams = _run(mk, good_load + [([99, 1], 5)])
        good, bad = reqs[:2], reqs[2]
        assert all(r.state == "done" for r in good)
        assert all(r.tokens == c.tokens
                   for r, c in zip(good, clean)), "fleet stream moved"
        ok, why = _audit_exactly_once(good, streams)
        assert ok, why
        assert bad.state == "failed"
        assert isinstance(bad.error, RequestQuarantined), bad.error
        counters = telemetry.registry().snapshot()["counters"]
        dead_letters = [e for e in rec.tail()
                        if e["name"] == "serve_request_quarantined"]
        ledger = {
            "stats_quarantined": eng.stats["quarantined"],
            "stats_failover_quarantined":
                eng.stats["failover_quarantined"],
            "info_quarantined_total":
                eng._failover_info["quarantined_total"],
            "counter_quarantined":
                counters.get("serving_requests_quarantined_total", 0),
            "dead_letter_events": len(dead_letters),
        }
        assert set(ledger.values()) == {1}, ledger
        assert eng._failover_info["state"] == "recovered"
    finally:
        telemetry.reset()
        events.reset()
    return ledger


def main() -> int:
    import numpy as np

    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    rng = np.random.RandomState(0)
    out = {"legs": {}}

    stub_load = _workload(rng, VOCAB, N_REQ)
    shapes = {
        "stub": lambda: GenerationEngine(
            StubBackend(2, 64, vocab_size=VOCAB), retries=1),
        "stub_paged": lambda: GenerationEngine(
            StubBackend(2, 64, vocab_size=VOCAB, block_size=8,
                        prefix_cache_bytes=1 << 20), retries=1),
    }
    if os.environ.get("SERVE_CHAOS_SKIP_LLAMA", "") != "1":
        import jax

        from sparkdl_tpu.models import llama as L

        cfg = L.LlamaConfig.tiny()
        model = L.LlamaModel(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4), np.int32))
        llama_load = _workload(rng, cfg.vocab_size, 4, max_new=(3, 5))

        def _llama(block_size=None):
            return GenerationEngine.from_model(
                model, variables, num_slots=2, max_len=64,
                block_size=block_size, temperature=0.0, min_bucket=8,
                queue_capacity=64, retries=1)

        shapes["llama"] = lambda: _llama()
        shapes["llama_paged"] = lambda: _llama(block_size=16)

    for name, mk in shapes.items():
        load = stub_load if name.startswith("stub") else llama_load
        out["legs"][name] = chaos_identity_leg(name, mk, load)

    out["legs"]["budget_counterfactual"] = budget_counterfactual_leg()
    out["legs"]["drain_resume"] = drain_resume_leg()
    out["legs"]["quarantine_ledger"] = quarantine_ledger_leg()

    out["ok"] = (
        all(v.get("token_identical", True)
            for v in out["legs"].values())
        and out["legs"]["drain_resume"]["resumed_identical"])
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
