#!/usr/bin/env python
"""Host-ingest microbench: decode→pack→stage rows/s with a STUB device.

The scoring path is host-bound (ROADMAP item 2: ~81 f32 / ~287 u8 img/s
against a 2541 img/s device roofline) and the host stages — Arrow decode,
pack/resize, pad, stage — are exactly the code ISSUE 7 rewrote. This
bench measures THOSE stages alone, with no device in the loop: the
"device" is a stub that only keeps the wire-byte ledger, so the number
is a pure host-ingest rate that runs (and lands in ``BENCH_*``) even
when the TPU probe reports ``backend_unavailable``, and re-verifies
unchanged on hardware later.

Legs (each: synthetic uniform uint8 image column → chunk → decode pool →
stage → stub put):

- ``f32_host``   — the PRE-ISSUE-7 feed: host resize+BGR→RGB+cast to
  float32 at the model size, per-batch pad allocation, thread decode.
- ``u8_fused``   — the post-ISSUE-7 default: ``imageColumnFeed`` ships
  the zero-copy storage-dtype view at native size (device would do
  flip/cast/resize inside the jitted program), staged through the
  reused ``StagingPool``.
- ``f32_process`` (``--process``) — the f32 host feed on the process
  decode pool: what ``SPARKDL_DECODE_BACKEND=process`` buys when decode
  is GIL-bound (the pure-python pack fallback; with the native packer
  installed decode releases the GIL and threads already scale).

Output (``--json``): per-leg ``rows_per_sec`` + ``wire_bytes_per_row`` +
staging stats, plus ``deltas`` (f32_host → u8_fused speedup and wire-byte
ratio) — the before/after evidence the bench record embeds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np
import pyarrow as pa

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_column(rows: int, h: int, w: int, seed: int = 0) -> pa.Array:
    """Uniform uint8 BGR image-struct column, the scorer's wire format."""
    from sparkdl_tpu.image import imageIO
    rng = np.random.default_rng(seed)
    # One base image + per-row roll: cheap to build, incompressible enough.
    base = rng.integers(0, 256, (h, w, 3), np.uint8)
    structs = [imageIO.imageArrayToStruct(np.roll(base, i, axis=0),
                                          origin=f"mem://{i}")
               for i in range(rows)]
    return pa.array(structs, type=imageIO.imageSchema)


def run_leg(col: pa.Array, *, fused: bool, staging: bool, batch_size: int,
            target: tuple[int, int], workers: int = 2,
            backend: str = "thread", min_seconds: float = 0.0) -> dict:
    """Decode→stage passes over ``col`` (repeated until ``min_seconds``
    of wall time so fast legs aren't timer noise); returns the record."""
    from sparkdl_tpu.core import ingest
    from sparkdl_tpu.image import imageIO
    th, tw = target
    n = len(col)
    chunks = [(s, min(batch_size, n - s)) for s in range(0, n, batch_size)]
    pool = ingest.StagingPool() if staging else None

    def decoded_stream():
        if backend == "process":
            ex = ingest.get_decode_executor(workers)

            def tasks():
                # picklable tasks, exactly as the scorer ships them: the
                # module-level factory + a COMPACTED chunk slice
                for s, length in chunks:
                    compact = pa.concat_arrays([col.slice(s, length)])
                    payload = (compact, th, tw, "RGB", "float32", fused,
                               True)
                    yield (ingest.decode_image_chunk, payload, length,
                           False, None)

            return (arr for arr, _info, _dur in ingest.windowed_apply(
                ingest.run_decode_task, tasks(), workers, workers,
                executor=ex))

        def decode(chunk):
            s, length = chunk
            return imageIO.imageColumnFeed(
                col.slice(s, length), th, tw, dtype=np.float32,
                channelOrder="RGB", fused=fused)

        # THE runtime window (ingest.windowed_apply) — the bench measures
        # the exact pipeline the scorer runs, not a stand-in.
        return ingest.windowed_apply(decode, chunks, workers, workers)

    def one_pass() -> tuple[int, int]:
        wire_bytes = rows = 0
        in_flight = []  # lease window of 2 — mimics the put/fetch overlap
        for arr in decoded_stream():
            if pool is not None:
                staged, nv, lease, _copied = ingest.stage_batch(
                    arr, batch_size, pool)
            else:
                # pre-ISSUE-7 pad: fresh concatenate per short batch
                nv = arr.shape[0]
                if nv < batch_size:
                    pad = np.broadcast_to(
                        arr[:1], (batch_size - nv,) + arr.shape[1:])
                    staged = np.concatenate([arr, pad], axis=0)
                else:
                    staged = arr
                lease = None
            # STUB device put: the ledger reads what device_put WOULD ship.
            wire_bytes += staged.nbytes
            rows += nv
            in_flight.append(lease)
            if len(in_flight) > 2:  # "fetch" completed → recyclable
                done = in_flight.pop(0)
                if pool is not None:
                    pool.release(done)
        # Drain the window: leaked leases would read as fresh allocs on
        # the next pass and under-report reuse across the min_seconds loop.
        while in_flight:
            done = in_flight.pop(0)
            if pool is not None:
                pool.release(done)
        return rows, wire_bytes

    rows = wire_bytes = passes = 0
    t0 = time.perf_counter()
    while True:
        r, b = one_pass()
        rows += r
        wire_bytes += b
        passes += 1
        if time.perf_counter() - t0 >= min_seconds:
            break
    dt = time.perf_counter() - t0
    return {
        "rows": rows, "passes": passes,
        "rows_per_sec": round(rows / dt, 2) if dt else 0.0,
        "seconds": round(dt, 4),
        "wire_bytes_per_row": int(wire_bytes / max(rows, 1)),
        "fused": fused, "staging": staging, "backend": backend,
        "workers": workers,
        "staging_stats": pool.stats() if pool is not None else None,
    }


def run(rows: int = 1000, stored: int = 112, target: int = 224,
        batch_size: int = 64, workers: int = 2,
        with_process: bool = False) -> dict:
    """All legs over one shared column; returns the full record."""
    col = build_column(rows, stored, stored)
    legs = {}
    # warmup decode machinery (imports, native packer) outside the bracket
    run_leg(col.slice(0, min(batch_size * 2, rows)), fused=False,
            staging=False, batch_size=batch_size, target=(target, target),
            workers=workers)
    legs["f32_host"] = run_leg(
        col, fused=False, staging=False, batch_size=batch_size,
        target=(target, target), workers=workers, min_seconds=0.5)
    legs["u8_fused"] = run_leg(
        col, fused=True, staging=True, batch_size=batch_size,
        target=(target, target), workers=workers, min_seconds=0.5)
    if with_process:
        legs["f32_process"] = run_leg(
            col, fused=False, staging=False, batch_size=batch_size,
            target=(target, target), workers=workers, backend="process",
            min_seconds=0.5)
    f32, u8 = legs["f32_host"], legs["u8_fused"]
    return {
        "metric": "host_ingest_rows_per_sec",
        "value": u8["rows_per_sec"],
        "unit": "rows/s",
        "config": {"rows": rows, "stored": stored, "target": target,
                   "batch_size": batch_size, "decode_workers": workers},
        "legs": legs,
        "deltas": {
            # before/after on the same workload: the ISSUE 7 acceptance
            # evidence (>=2x rows/s on the f32 image path, >=4x fewer
            # wire bytes on the u8 path).
            "rows_per_sec_vs_f32_host": round(
                u8["rows_per_sec"] / f32["rows_per_sec"], 2)
            if f32["rows_per_sec"] else None,
            "wire_bytes_ratio_f32_over_u8": round(
                f32["wire_bytes_per_row"] / u8["wire_bytes_per_row"], 2)
            if u8["wire_bytes_per_row"] else None,
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    # 1000 deliberately NOT divisible by the batch size: the short tail
    # chunk each pass is what drives stage_batch through the StagingPool
    # (an all-full-batch config would pass through and prove nothing
    # about staging reuse).
    ap.add_argument("--rows", type=int, default=1000)
    ap.add_argument("--stored", type=int, default=112,
                    help="stored (native) image edge")
    ap.add_argument("--target", type=int, default=224,
                    help="model input edge")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--process", action="store_true",
                    help="also run the f32 feed on the process decode pool")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    rec = run(rows=args.rows, stored=args.stored, target=args.target,
              batch_size=args.batch_size, workers=args.workers,
              with_process=args.process)
    if args.json:
        print(json.dumps(rec, indent=2))
    else:
        for name, leg in rec["legs"].items():
            print(f"{name:12s} {leg['rows_per_sec']:10.1f} rows/s  "
                  f"{leg['wire_bytes_per_row']:9d} B/row")
        d = rec["deltas"]
        print(f"u8_fused vs f32_host: {d['rows_per_sec_vs_f32_host']}x "
              f"rows/s, {d['wire_bytes_ratio_f32_over_u8']}x fewer "
              f"wire bytes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
