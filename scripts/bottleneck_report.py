#!/usr/bin/env python
"""Bottleneck attribution report over flight-recorder span streams and
telemetry snapshots (ISSUE 6, layer 3).

Consumes the per-rank ``events_rank{i}.jsonl`` streams a run left under
``SPARKDL_EVENT_DIR`` (supervised gangs stream one level down in
``gang-*/`` subdirs — picked up automatically) and prints a per-stage
utilization table: busy seconds, wall-busy fraction, exclusive time,
achieved parallelism, rows and bytes moved — then names the dominant
stage with the Amdahl-style projection ("decode 94% busy → ≤1.06x from
fixing anything else"). With ``--metrics-dir`` it also prints the
gang-level aggregate of the live telemetry snapshots
(``metrics_rank{i}.json``, written by ``SPARKDL_METRICS_DIR`` runs).

Usage:
    python scripts/bottleneck_report.py EVENT_DIR [--metrics-dir DIR]
        [--json]

Exit codes: 0 = report printed; 2 = no span evidence found.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# analysis/telemetry are stdlib-only; the package import pulls jax into
# the interpreter (inert — no device query, so no backend init: the same
# rule the supervising launcher rides).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from sparkdl_tpu.runner import analysis, telemetry  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-stage utilization + bottleneck attribution from "
                    "flight-recorder span streams")
    ap.add_argument("event_dir",
                    help="directory of events_rank*.jsonl streams "
                         "(SPARKDL_EVENT_DIR; gang-*/ subdirs included)")
    ap.add_argument("--metrics-dir", default=None,
                    help="directory of metrics_rank*.json telemetry "
                         "snapshots (SPARKDL_METRICS_DIR) to aggregate "
                         "alongside")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object instead "
                         "of the table")
    ns = ap.parse_args(argv)

    recs = analysis.load_event_dir(ns.event_dir)
    rep = analysis.analyze(events=recs) if recs else None
    # ISSUE 13: when the stream holds serve_* spans, the stage table is
    # not the whole story — append the request-trace tail (slowest
    # requests, phase-attributed) and the SLO compliance block so the
    # report states compliance, not just percentiles.
    req = analysis.request_summary(recs) if recs else None
    agg = telemetry.aggregate_snapshots(ns.metrics_dir) \
        if ns.metrics_dir else None
    if rep is None and agg is None:
        print(f"bottleneck_report: no span streams or snapshots under "
              f"{ns.event_dir}"
              + (f" / {ns.metrics_dir}" if ns.metrics_dir else ""),
              file=sys.stderr)
        return 2

    if ns.json:
        print(json.dumps({"report": rep, "gang_metrics": agg,
                          "requests": req}, default=str))
        return 0
    if rep is not None:
        print(analysis.format_report(rep))
    if agg is not None:
        print(f"\ngang telemetry ({agg['n_ranks']} rank(s), elapsed "
              f"{agg['elapsed_s']:.3f}s):")
        for name, st in sorted(agg["stages"].items(),
                               key=lambda kv: -kv[1]["busy_frac"]):
            print(f"  {name}: busy {st['busy_s']:.3f}s "
                  f"({100 * st['busy_frac']:.1f}% of gang rank-time), "
                  f"rows {st['rows']}, "
                  f"max_concurrency {st['max_concurrency']}")
        for name, n in sorted((agg.get("events") or {}).items()):
            print(f"  event {name}: {n}")
        for name, g in sorted((agg.get("gauges") or {}).items()):
            # Pool gauges make an HBM-bound engine attributable: a
            # serving_kv_blocks_free floor near 0 with admission waits
            # in the engine stats IS the bottleneck, no span needed.
            print(f"  gauge {name}: {g.get('value', 0):g} "
                  f"(high-water {g.get('max', 0):g})")
        for name, h in sorted((agg.get("histograms") or {}).items()):
            # One derivation for everyone: telemetry.histogram_quantile
            # is the same helper the serving bench uses, so a latency
            # percentile printed here can never disagree with the bench
            # on the same snapshot.
            qs = {q: telemetry.histogram_quantile(h, q)
                  for q in (0.5, 0.95, 0.99)}
            if qs[0.5] is None:
                continue
            print(f"  {name}: p50 {qs[0.5]:.4g}s  p95 {qs[0.95]:.4g}s  "
                  f"p99 {qs[0.99]:.4g}s  (n={h.get('count', 0)}, "
                  f"bucket-resolution)")
        spec = (agg.get("histograms") or {}).get("serve_spec_accept_len")
        if spec and spec.get("count"):
            # The speculative-decode observable: tokens committed per
            # verify window (1 = drafts never accepted = the k=0
            # economics; k+1 = every draft accepted). A dispatch-bound
            # engine's tokens/s scales with this mean.
            print(f"  speculation: mean accepted length "
                  f"{spec['sum'] / spec['count']:.2f} tokens/verify "
                  f"(n={spec['count']} verify windows)")
    if req is not None:
        print()
        print(analysis.format_request_summary(req))
        print("(per-request detail: scripts/request_report.py "
              f"{ns.event_dir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
