#!/usr/bin/env python
"""Serving throughput bench — stall-free chunked prefill + shared-prefix
KV reuse vs the PR 8 blocking engine and the static whole-batch path
(ISSUE 8 + ISSUE 10 acceptance evidence).

Workload: ``BENCH_SERVE_REQUESTS`` requests in a chat-serving shape —
every prompt opens with a shared 32-token preamble (the chat-template /
system-prompt head real fleets share across ALL traffic); short
requests draw from a pool of repeated prompts (lengths 35–56, the
FAQ/retry-storm shape) with a long-tail output mix (1-in-16 wants 48
tokens, 4x the median); **1-in-8 requests carry a 192-token prompt**
(preamble + shared 144-token document + 16 distinct tokens — the RAG
shape: long shared context, short answer). Long prompts are exactly
what the blocking scheduler stalls on and what the prefix cache makes
cheap.

Measurements per run:

- **stall-free engine legs** at closed-loop client concurrency 1/8/32:
  aggregate tokens/s, request-latency + TTFT percentiles (via
  ``telemetry.histogram_quantile``), per-leg ``decode_stall_s`` and
  prefix-cache hit/reuse counters.
- **blocking comparator** (``stall_free=False`` — the PR 8 engine,
  bucketed whole-prompt refills, no prefix reuse) at the top
  concurrency on the same workload: ``speedup_vs_blocking``,
  ``ttft_p99_ratio`` and ``decode_stall_ratio`` are the ISSUE 10
  acceptance numbers.
- **static comparator**: the same requests in arrival order, grouped
  into ``num_slots``-sized whole batches through
  ``models.llama.generate`` — the pre-ISSUE-8 serving shape.
- **re-trace pin**: ``GLOBAL_COMPILE_CACHE.signatures()`` for the slot
  decode-step program, captured after warmup and after the measured
  runs — ``decode_retrace_after_warmup`` must be 0 (refills, chunked
  prefills and prefix-cache copies never re-trace the decode step).

``mode="stub"`` swaps the model for the jax-free
``serving.StubBackend`` with a synthetic per-call device-time model
(``step_s`` per decode iteration, ``prefill_tok_s`` per prompt token —
per-token prefill cost is what makes bucket padding and prefix reuse
show up in wall time the way they do on hardware) and walks the static
schedule with the same stub timings — the scheduler win stays
measurable inside a ``backend_unavailable`` bench record (the
never-host-blind rule from the host-ingest leg). The stub leg uses a
smaller chunk (8) than the CPU llama leg (32): chunking granularity is
a per-call-overhead tradeoff, and the stub models an async device where
per-call overhead ≈ 0 while the CPU pays ~10 ms dispatch per jitted
call.

Standalone:  JAX_PLATFORMS=cpu python scripts/serve_bench.py [--stub]
"""

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

_DEF_REQUESTS = 288
# Slot count vs per-iteration prefill budget: the stall-free scheduler
# feeds AT MOST one chunk per iteration, so the slot-table churn
# (slots / median output length) must stay under ~one refill per
# iteration or admission starves occupancy. 8 slots against the
# median-12-token output mix keeps churn ~0.7 refills/iteration —
# in-budget for both schedulers, so the comparison measures prefill
# economics, not a misconfigured slot table.
_DEF_SLOTS = 8
_DEF_MAX_LEN = 384  # fits bucket(192)=256 + out for the blocking leg
_PROMPT_LENS = (3, 6, 12, 24)   # short-request body lengths (post-preamble)
# Long-tail output mix for the short classes: 1-in-16 wants 48 tokens
# (4x the median). A static whole batch then usually carries >= 1 long
# request and decodes ~48 steps for a ~13-token mean — the whole-batch
# waste in-flight batching removes. (PR 8's 192-token output tail moved
# to the PROMPT side this round: the 1-in-8 192-token-prompt class is
# what the stall-free scheduler is measured on; a 192-token output tail
# would hoard the 8-slot table for whole windows and mask TTFT behind
# slot scarcity in BOTH schedulers.)
_OUT_CHOICES = (8, 12, 16, 48)
_OUT_PROBS = (0.45, 0.3, 0.1875, 0.0625)
_PREAMBLE = 32      # shared head on EVERY prompt (chat template)
_DOC = 144          # shared long-context document (long class)
_LONG_TAIL = 16     # distinct tokens per long request
_LONG_OUT = 8       # RAG shape: long prompt, short answer
_LONG_FRAC = 0.125  # 1-in-8 requests are prompt-length 192
_SHORT_POOL = 16    # distinct short prompts (repeats = cache hits)
_PAD_TO_COL = _PREAMBLE + _DOC + _LONG_TAIL  # static column width (192)
_MIN_BUCKET = 8
_CHUNK_LLAMA = 24   # CPU: ~10ms dispatch per call -> coarse chunks
_CHUNK_STUB = 8     # async-device model: fine chunks, tighter reuse


def make_workload(n: int, vocab: int, seed: int = 0):
    """(prompt_ids, max_new_tokens) pairs (see module doc): shared
    preamble on everything, repeated short prompts, and a 1-in-8
    prompt-length-192 class sharing a 160-token head."""
    rng = np.random.RandomState(seed)
    preamble = rng.randint(0, vocab, _PREAMBLE).tolist()
    doc = rng.randint(0, vocab, _DOC).tolist()
    pool = [preamble + rng.randint(
        0, vocab, int(rng.choice(_PROMPT_LENS))).tolist()
        for _ in range(_SHORT_POOL)]
    out = []
    for _ in range(n):
        if rng.rand() < _LONG_FRAC:
            prompt = preamble + doc + rng.randint(0, vocab,
                                                  _LONG_TAIL).tolist()
            new = _LONG_OUT
        else:
            prompt = pool[rng.randint(len(pool))]
            new = int(rng.choice(_OUT_CHOICES, p=_OUT_PROBS))
        out.append((prompt, new))
    return out


def _quantiles(hist_snap):
    from sparkdl_tpu.runner.telemetry import histogram_quantile
    return {f"p{int(q * 100)}": histogram_quantile(hist_snap, q)
            for q in (0.5, 0.95, 0.99)}


def run_engine_leg(make_engine, workload, concurrency: int,
                   timeout_s: float = 600.0) -> dict:
    """Drive the workload through a fresh engine with ``concurrency``
    closed-loop clients; returns tokens/s + latency percentiles."""
    from sparkdl_tpu.runner import telemetry
    telemetry.reset()
    telemetry.start()  # registry-only plane: histograms for percentiles
    eng = make_engine()
    handles: list = []
    hlock = threading.Lock()
    errors: list = []

    def client(chunk):
        try:
            for prompt, new in chunk:
                h = eng.submit(prompt, max_new_tokens=new)
                with hlock:
                    handles.append(h)
                h.result(timeout=timeout_s)  # closed loop: wait, then next
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            errors.append(f"{type(e).__name__}: {e}")

    chunks = [workload[i::concurrency] for i in range(concurrency)]
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in chunks if c]
    eng.start()
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s)
    wall = time.perf_counter() - t0
    eng.stop(drain=True, timeout=30)
    tokens = sum(len(h.tokens) for h in handles)
    reg = telemetry.registry()
    lat = reg.histogram("serving_request_latency_s").snapshot()
    ttft = reg.histogram("serving_ttft_s").snapshot()
    snap = eng.snapshot()
    traces = telemetry.request_traces().traces()
    slowest = telemetry.request_traces().slowest()
    telemetry.reset()
    rec = {
        "concurrency": concurrency,
        "requests": len(handles),
        "completed": snap["completed"],
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
        "latency_s": _quantiles(lat),
        "ttft_s": _quantiles(ttft),
        "peak_queue_depth": snap["peak_queue_depth"],
        "peak_slots_busy": snap["peak_slots_busy"],
        "decode_steps": snap["steps"],
        # ISSUE 10: the stall ledger + prefix-cache economics per leg
        "stall_free": snap["stall_free"],
        "decode_stall_s": round(snap["decode_stall_s"], 4),
        "decode_stall_events": snap["decode_stall_events"],
        "prefill_chunks": snap["prefill_chunks"],
    }
    # ISSUE 13: per-leg SLO compliance + slowest-trace phase breakdown.
    # Thresholds come from the SPARKDL_SLO_* knobs when armed, else
    # bench defaults generous enough for the CPU legs — the point is
    # that BOTH the healthy and backend_unavailable records state
    # compliance, not just percentiles. Compliance is computed over the
    # assembled request traces (exact values, and it exercises the
    # collector end-to-end: the attribution residual below is the
    # "phases sum to latency" acceptance observable).
    ttft_thr = float(os.environ.get("SPARKDL_SLO_TTFT_S") or 2.5)
    lat_thr = float(os.environ.get("SPARKDL_SLO_LATENCY_S") or 60.0)
    rec["slo"] = {
        # compliance off the cumulative histograms (every request — the
        # trace ring is bounded), interpolated inside the threshold's
        # bucket by the same helper the live burn-rate monitor uses
        "ttft_threshold_s": ttft_thr,
        "latency_threshold_s": lat_thr,
        "ttft_compliance": telemetry.histogram_fraction_below(
            ttft, ttft_thr),
        "latency_compliance": telemetry.histogram_fraction_below(
            lat, lat_thr),
    }
    if traces:
        clean = [t for t in traces if t.get("finish") != "error"
                 and not t.get("partial") and t["latency_s"] > 0]
        unattr = [abs(t["unattributed_s"]) / t["latency_s"]
                  for t in clean]
        rec["trace_attribution"] = {
            "traces": len(traces),
            "max_unattributed_frac": round(max(unattr), 4)
            if unattr else None,
            "within_5pct": bool(unattr) and max(unattr) <= 0.05,
        }
        if slowest:
            top = slowest[0]
            rec["slowest_trace"] = {
                k: top.get(k) for k in (
                    "request", "latency_s", "ttft_s", "queue_s",
                    "prefill_s", "prefill_wait_s", "decode_s",
                    "draft_s", "block_stall_s", "unattributed_s",
                    "tokens_out", "preemptions", "dominant_phase",
                    "finish")}
    if snap.get("paged"):
        # ISSUE 11 pool evidence per leg: utilization/share from the
        # allocator, shared-block high-water from the telemetry gauge
        # (end-of-run shares drop to trie-only refs, so the peak is the
        # concurrency observable), admission-wait stats from the engine.
        shared_hw = reg.gauge("serving_kv_blocks_shared").snapshot()["max"]
        pool = snap.get("kv_pool") or {}
        rec["kv_pool"] = pool
        rec["kv_pool_utilization"] = pool.get("peak_utilization")
        rec["blocks_shared_peak"] = shared_hw
        rec["blocks_shared_frac"] = round(
            shared_hw / pool["blocks_total"], 4) \
            if pool.get("blocks_total") else None
        rec["admission_block_waits"] = snap["admission_block_waits"]
        rec["block_stall_events"] = snap["block_stall_events"]
        rec["preemptions"] = snap["preemptions"]
    if snap.get("prefix_cache"):
        # key set differs by backend: the byte-payload LRU reports
        # entries/bytes, the paged radix trie blocks/block_size
        ps = snap["prefix_cache"]
        rec["prefix_cache"] = {k: ps[k] for k in (
            "hits", "misses", "hit_rate", "reused_tokens", "entries",
            "evictions", "bytes", "blocks", "inserted_blocks")
            if k in ps}
    if snap.get("spec_k"):
        # ISSUE 12 speculation ledger per leg: acceptance rate over
        # offered drafts + mean committed tokens per verify window
        # (1 = the k=0 economics, k+1 = every draft accepted) from the
        # serve_spec_accept_len histogram.
        acc = snap["spec_tokens_accepted"]
        rej = snap["spec_tokens_rejected"]
        h = reg.histogram("serve_spec_accept_len").snapshot()
        rec["spec_k"] = snap["spec_k"]
        rec["spec_verifies"] = snap["spec_verifies"]
        rec["spec_accept_rate"] = round(acc / (acc + rej), 4) \
            if acc + rej else None
        rec["spec_mean_accept_len"] = round(h["sum"] / h["count"], 3) \
            if h["count"] else None
    if errors:
        rec["errors"] = errors[:5]
    return rec


# ---------------------------------------------------------------------------
# llama mode (real model — CPU or TPU, whatever the ambient platform is)
# ---------------------------------------------------------------------------

def _bench_config():
    """The serving-bench model: big enough that one decode step's (and
    one prefill chunk's) compute dominates per-call dispatch overhead —
    on CPU each jitted call pays ~10 ms of Python/XLA dispatch, so a
    too-small model measures the dispatcher, understating the prefill
    economics the prefix cache changes — small enough to stay inside a
    bench leg's budget everywhere. (Grew h256x4 -> h1024x2 with ISSUE
    10: the chunked-prefill comparison is about prompt-token compute,
    and on CPU each jitted call carries ~10 ms of fixed dispatch —
    wider-and-shallower raises compute per token without raising call
    count or compile time, so the measured economics are the device's,
    not the dispatcher's.)"""
    from sparkdl_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=2048, hidden_size=1024, num_layers=2,
                       num_heads=8, num_kv_heads=4,
                       intermediate_size=2048, rope_theta=10000.0)


def _compare_records(rec: dict, sf_top: dict, bl_top: dict):
    """The ISSUE 10 acceptance ratios: stall-free vs the PR 8 blocking
    engine on the same workload at the same concurrency."""
    if sf_top.get("tokens_s") and bl_top.get("tokens_s"):
        rec["speedup_vs_blocking"] = round(
            sf_top["tokens_s"] / bl_top["tokens_s"], 2)
    sf_p99 = (sf_top.get("ttft_s") or {}).get("p99")
    bl_p99 = (bl_top.get("ttft_s") or {}).get("p99")
    if sf_p99 and bl_p99:
        rec["ttft_p99_ratio"] = round(bl_p99 / sf_p99, 2)
    if sf_top.get("decode_stall_s") and bl_top.get("decode_stall_s"):
        rec["decode_stall_ratio"] = round(
            bl_top["decode_stall_s"] / sf_top["decode_stall_s"], 2)
    rec["prefix_cache"] = sf_top.get("prefix_cache")


def _run_llama(n_requests: int, num_slots: int, max_len: int,
               concurrencies) -> dict:
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    cfg = _bench_config()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    workload = make_workload(n_requests, cfg.vocab_size)
    chunk = int(os.environ.get("BENCH_SERVE_CHUNK", _CHUNK_LLAMA))

    def make_engine(stall_free: bool = True):
        return GenerationEngine.from_model(
            model, variables, num_slots=num_slots, max_len=max_len,
            min_bucket=_MIN_BUCKET, queue_capacity=max(64, n_requests),
            stall_free=stall_free, prefill_chunk=chunk)

    # Greedy continuous batching must be token-identical to the static
    # path — spot-check a few requests against generate() FIRST (its
    # small private engine compiles a 2-slot decode program that must
    # not count against the re-trace pin below). Includes one long
    # prompt so the chunked path and a prefix-cache hit are in scope.
    spot = [w for w in workload if len(w[0]) > 100][:1] + workload[:3]
    spot_ok = _spot_check(model, variables, spot, max_len)

    # -- warmup: compile every program all paths will use -----------------
    eng = make_engine()  # chunked: chunk + decode + prefix copy programs
    for prompt, _ in spot:
        eng.submit(prompt, max_new_tokens=2)
        eng.run_until_idle()  # drain so repeats commit/hit the prefix LRU
    for prompt, _ in spot:
        eng.submit(prompt, max_new_tokens=2)
        eng.run_until_idle()
    engb = make_engine(stall_free=False)  # bucketed whole-prompt prefills
    for prompt, _ in spot:
        engb.submit(prompt, max_new_tokens=2)
    engb.run_until_idle()
    # static path: one (batch, pad) prefill + one decode program per
    # distinct group-max output length
    for n_new in sorted(set(_OUT_CHOICES + (_LONG_OUT,))):
        _static_pass(model, variables,
                     [([1, 2, 3], n_new)] * num_slots, num_slots, max_len)
    sig_prefill = GLOBAL_COMPILE_CACHE.signatures("serve_prefill")
    sig_chunk = GLOBAL_COMPILE_CACHE.signatures("serve_prefill_chunk")
    sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

    # -- stall-free engine legs -------------------------------------------
    # Closed-loop clients: low concurrency can't keep the slot table
    # full, so a c=1 leg over the whole workload would run for minutes
    # serving one slot — scale the request count with the offered load
    # (tokens/s normalizes it away; the FULL workload runs at max
    # concurrency, which is the headline + comparator leg).
    legs = {}
    for c in concurrencies:
        n_leg = len(workload) if c >= max(concurrencies) else \
            max(24, min(len(workload), c * 12))
        legs[str(c)] = run_engine_leg(make_engine, workload[:n_leg], c)

    # -- blocking (PR 8) comparator at top concurrency --------------------
    top_c = max(concurrencies)
    blocking = run_engine_leg(lambda: make_engine(stall_free=False),
                              workload, top_c)

    # -- static whole-batch comparator ------------------------------------
    static = _static_pass(model, variables, workload, num_slots, max_len)

    retrace = (GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
               - sig_decode)
    rec = {
        "mode": "llama",
        "model": {"vocab_size": cfg.vocab_size,
                  "hidden_size": cfg.hidden_size,
                  "num_layers": cfg.num_layers,
                  "num_heads": cfg.num_heads,
                  "num_kv_heads": cfg.num_kv_heads,
                  "intermediate_size": cfg.intermediate_size},
        "platform": jax.default_backend(),
        "num_slots": num_slots,
        "max_len": max_len,
        "prefill_chunk": chunk,
        "requests": n_requests,
        "engine": legs,
        "engine_blocking": blocking,
        "static": static,
        "prefill_buckets_compiled": sig_prefill,
        "chunk_programs_compiled": sig_chunk,
        "decode_retrace_after_warmup": retrace,
        "decode_signatures": GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step"),
    }
    top = legs.get(str(top_c), {})
    _compare_records(rec, top, blocking)
    if top.get("tokens_s") and static.get("tokens_s"):
        rec["speedup_vs_static"] = round(
            top["tokens_s"] / static["tokens_s"], 2)
    rec["token_identical_spot_check"] = spot_ok
    return rec


def _static_pass(model, variables, workload, batch: int,
                 max_len: int) -> dict:
    """The pre-ISSUE-8 serving shape: whole batches in arrival order;
    every batch decodes max(out_lens) steps (EOS-free greedy — rows that
    finished their requested length keep decoding until the longest row
    is done, exactly the waste continuous batching removes). Short tail
    batches are padded to the full batch width by repeating the last
    request so one (batch, pad) program serves every group; only
    requested tokens count."""
    from sparkdl_tpu.models import llama as L
    lat: list[float] = []
    tokens = 0
    t0 = time.perf_counter()
    for i in range(0, len(workload), batch):
        grp = list(workload[i:i + batch])
        real = len(grp)
        while len(grp) < batch:
            grp.append(grp[-1])
        prompts = [p for p, _ in grp]
        outs = [n for _, n in grp]
        ids, lens = L.left_pad_prompts(prompts, pad_to=_PAD_TO_COL)
        out = L.generate(model, variables, np.asarray(ids),
                         int(max(outs)), pad_lens=np.asarray(lens),
                         pad_to=max_len)
        np.asarray(out)  # host fetch = the timing barrier
        done = time.perf_counter() - t0
        tokens += sum(outs[:real])
        lat.extend([done] * real)  # all requests arrived at t0
    wall = time.perf_counter() - t0
    lat_arr = np.asarray(lat) if lat else np.asarray([0.0])
    return {
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
        "batches": -(-len(workload) // batch),
        "latency_s": {"p50": round(float(np.percentile(lat_arr, 50)), 6),
                      "p95": round(float(np.percentile(lat_arr, 95)), 6),
                      "p99": round(float(np.percentile(lat_arr, 99)), 6)},
    }


def _spot_check(model, variables, pairs, max_len: int) -> bool:
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine
    eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                      max_len=max_len,
                                      min_bucket=_MIN_BUCKET)
    handles = [eng.submit(p, max_new_tokens=n) for p, n in pairs]
    eng.run_until_idle()
    for (p, n), h in zip(pairs, handles):
        ids, lens = L.left_pad_prompts([p])
        ref = np.asarray(L.generate(
            model, variables, np.asarray(ids), n,
            pad_lens=np.asarray(lens), pad_to=max_len))
        if h.result(1) != ref[0][int(lens[0]) + len(p):].tolist():
            return False
    return True


# ---------------------------------------------------------------------------
# stub mode (no jax compute — scheduler throughput during an outage)
# ---------------------------------------------------------------------------

def _run_stub(n_requests: int, num_slots: int, max_len: int,
              concurrencies, step_s: float,
              prefill_tok_s: float) -> dict:
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    workload = make_workload(n_requests, vocab=32000)
    chunk = int(os.environ.get("BENCH_SERVE_CHUNK", _CHUNK_STUB))

    def make_engine(stall_free: bool = True):
        return GenerationEngine(
            StubBackend(num_slots, max_len, step_s=step_s,
                        prefill_tok_s=prefill_tok_s),
            min_bucket=_MIN_BUCKET, queue_capacity=max(64, n_requests),
            stall_free=stall_free, prefill_chunk=chunk)

    legs = {}
    for c in concurrencies:
        legs[str(c)] = run_engine_leg(make_engine, workload, c)

    # the PR 8 engine on the same stub timings: bucketed whole-prompt
    # refills, no prefix reuse — the ISSUE 10 comparator
    top_c = max(concurrencies)
    blocking = run_engine_leg(lambda: make_engine(stall_free=False),
                              workload, top_c)

    # Static comparator with the SAME stub timings: whole batches, each
    # paying its prefill (column width x per-token cost) once and
    # max(out_lens) decode steps — slept PER STEP, exactly as the
    # engine's stub pays per step, so OS sleep granularity inflates
    # both sides equally and the ratio measures scheduling (steps
    # issued), not timer resolution.
    tokens = 0
    t0 = time.perf_counter()
    for i in range(0, len(workload), num_slots):
        grp = workload[i:i + num_slots]
        time.sleep(prefill_tok_s * _PAD_TO_COL)
        for _ in range(max(n for _, n in grp)):
            time.sleep(step_s)
        tokens += sum(n for _, n in grp)
    wall = time.perf_counter() - t0
    static = {"tokens": tokens, "wall_s": round(wall, 4),
              "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
              "batches": -(-len(workload) // num_slots)}
    rec = {
        "mode": "stub",
        "step_s": step_s,
        "prefill_tok_s": prefill_tok_s,
        "prefill_chunk": chunk,
        "num_slots": num_slots,
        "max_len": max_len,
        "requests": n_requests,
        "engine": legs,
        "engine_blocking": blocking,
        "static": static,
    }
    top = legs.get(str(top_c), {})
    _compare_records(rec, top, blocking)
    if top.get("tokens_s") and static.get("tokens_s"):
        rec["speedup_vs_static"] = round(
            top["tokens_s"] / static["tokens_s"], 2)
    return rec


# ---------------------------------------------------------------------------
# high-churn paged-vs-per-slot leg (ISSUE 11)
# ---------------------------------------------------------------------------

_CHURN_PREAMBLE = 32   # shared head on every churn prompt (radix target)
_CHURN_BODY = (8, 12, 16, 24)   # short distinct bodies
_CHURN_OUT = (4, 6, 8)          # SHORT outputs: slot churn is the load
_CHURN_BLOCK = 16


def attn_positions_model(workload, block_size: int, max_len: int):
    """Deterministic per-decode-step attention-READ model for a paged
    engine (ISSUE 15): the gather-view path reads every slot's whole
    table (``max_blocks × block_size`` positions per slot per step)
    while the paged flash-decode kernel reads only the slot's LIVE
    blocks (fill rounded up to a block). Returns
    ``(gather_positions, kernel_positions)`` summed over every decode
    step of the workload — the HBM-traffic claim the kernel makes,
    computable host-side (no engine instrumentation, so it rides
    ``backend_unavailable`` records too)."""
    mb = -(-max_len // block_size)
    gather = sum(n * mb * block_size for _, n in workload)
    kernel = sum(
        sum(-(-(len(p) + i + 1) // block_size) * block_size
            for i in range(n))
        for p, n in workload)
    return gather, kernel


# K/V bytes one cache position costs in the serve-bench llama model
# (_bench_config: 2 (K+V) x 4 kv heads x 128 head_dim x 4 B f32 x
# 2 layers) — the reference dtype for the analytic bytes estimate.
_BYTES_PER_POSITION = 2 * 4 * 128 * 4 * 2


def kv_bytes_per_position(kv_dtype: str | None = None, *,
                          kv_heads: int = 4, head_dim: int = 128,
                          layers: int = 2,
                          block_size: int = _CHURN_BLOCK) -> float:
    """ISSUE 18 — the bytes one cache position costs at a given KV
    storage dtype, INCLUDING the amortized per-block scale plane.
    f32/None is the reference (== ``_BYTES_PER_POSITION`` at the bench
    model's shape); int8/fp8 store 1-byte codes plus a ``[Hkv, 2]``
    f32 scale row per block per layer (``8·Hkv·layers / block_size``
    bytes per position). Deterministic and host-side, like
    :func:`attn_positions_model` — so the quant/f32 ratio rides the
    ``backend_unavailable`` records too."""
    if kv_dtype in (None, "", "float", "f32", "float32"):
        return float(2 * kv_heads * head_dim * 4 * layers)
    if kv_dtype not in ("int8", "fp8"):
        raise ValueError(f"unknown kv_dtype {kv_dtype!r} "
                         "(float/int8/fp8)")
    codes = 2 * kv_heads * head_dim * 1 * layers
    scales = kv_heads * 2 * 4 * layers / block_size
    return codes + scales


def make_churn_workload(n: int, vocab: int = 32000, seed: int = 3):
    """Short-output many-request chat mix: every prompt opens with the
    same 32-token preamble, bodies are short and distinct, outputs 4-8
    tokens — the request-turnover shape where admission pacing and the
    per-slot ``max_len`` reservation (NOT decode compute) bound
    throughput."""
    rng = np.random.RandomState(seed)
    preamble = rng.randint(0, vocab, _CHURN_PREAMBLE).tolist()
    out = []
    for _ in range(n):
        body = rng.randint(0, vocab,
                           int(rng.choice(_CHURN_BODY))).tolist()
        out.append((preamble + body,
                    int(rng.choice(_CHURN_OUT))))
    return out


def run_paged_churn_comparison(n_requests: int = 192,
                               step_s: float = 0.0015,
                               prefill_tok_s: float = 1e-4,
                               kv_dtype: str | None = None) -> dict:
    """ISSUE 11 acceptance leg, jax-free: the SAME KV byte pool serves
    8 per-slot rows (PR 9 engine — ``8 × max_len`` positions reserved
    up front) vs a paged engine with 32 slots over a block pool of
    identical size. Short outputs churn the slot table; the per-slot
    engine is bounded by 8 concurrent requests while the paged engine
    is bounded by what the pool actually holds — effective concurrency,
    tokens/s, ``kv_pool_utilization`` and ``blocks_shared_frac`` (the
    shared preamble resident as ONE physical block set) are the record.
    The multi-chunk prefill budget (8 chunks/iteration) is what lets
    admission keep up with 32-slot churn."""
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    slots_legacy, max_len = 8, 256
    pool_positions = slots_legacy * max_len          # FIXED byte pool
    pool_blocks = pool_positions // _CHURN_BLOCK + 1  # + trash block
    slots_paged = 32
    workload = make_churn_workload(n_requests)
    chunk = _CHURN_BLOCK

    def legacy_engine():
        return GenerationEngine(
            StubBackend(slots_legacy, max_len, step_s=step_s,
                        prefill_tok_s=prefill_tok_s),
            queue_capacity=max(64, n_requests), prefill_chunk=chunk)

    def paged_engine():
        return GenerationEngine(
            StubBackend(slots_paged, max_len, step_s=step_s,
                        prefill_tok_s=prefill_tok_s,
                        block_size=_CHURN_BLOCK, pool_blocks=pool_blocks),
            queue_capacity=max(64, n_requests), prefill_chunk=chunk,
            # 32-slot churn needs ~slots/median-out ≈ 5 refills per
            # iteration; 8 chunks covers that with radix hits (1-2
            # tail chunks per request) — the one-chunk PR 9 budget is
            # exactly what capped the old engine at ~1 refill/iteration
            prefill_budget=8 * chunk)

    legs = {}
    for name, make in (("per_slot", legacy_engine), ("paged",
                                                     paged_engine)):
        legs[name] = run_engine_leg(make, workload, concurrency=32)
    paged = legs["paged"]

    # ISSUE 15 paged-kernel sub-leg (rides BOTH the healthy and the
    # backend_unavailable record — never-host-blind): the same paged
    # engine with the kernel knob set. The stub backend has no
    # attention at all, so the measured on/off tokens/s delta here is
    # a scheduler-invariance check (~1.0x — the kernel must not change
    # the jax-free scheduling), while the HBM claim is the
    # deterministic attention-read model: gather-view bytes vs
    # kernel bytes per decode step over this exact workload. The
    # on-chip measured speedup is left to the next TPU probe (the
    # real-model CPU leg in the llama record pins token identity).
    prev = os.environ.get("SPARKDL_SERVE_PAGED_KERNEL")
    try:
        os.environ["SPARKDL_SERVE_PAGED_KERNEL"] = "1"
        kernel_on = run_engine_leg(paged_engine, workload,
                                   concurrency=32)
    finally:
        if prev is None:
            os.environ.pop("SPARKDL_SERVE_PAGED_KERNEL", None)
        else:
            os.environ["SPARKDL_SERVE_PAGED_KERNEL"] = prev
    gather_pos, kernel_pos = attn_positions_model(
        workload, _CHURN_BLOCK, max_len)
    paged_kernel = {
        "kernel_on_tokens_s": kernel_on.get("tokens_s"),
        "kernel_off_tokens_s": paged.get("tokens_s"),
        "attn_bytes_per_step": {
            "gather_view": int(gather_pos * _BYTES_PER_POSITION
                               // max(1, kernel_on.get("decode_steps")
                                      or 1)),
            "kernel": int(kernel_pos * _BYTES_PER_POSITION
                          // max(1, kernel_on.get("decode_steps") or 1)),
        },
        "attn_bytes_ratio": round(gather_pos / kernel_pos, 2)
        if kernel_pos else None,
        "honest_label": (
            "stub backend: no attention runs, so the on/off tokens/s "
            "pair is an A/A scheduler-invariance check (~1.0, pure "
            "timing noise — NOT kernel evidence); the claim-bearing "
            "number is modeled_hbm_speedup, the deterministic "
            "per-decode-step attention-read model at the serve-bench "
            "llama model's K/V bytes/position (decode is "
            "bandwidth-bound, so bytes ratio ~ modeled speedup) — "
            "the measured on-chip speedup needs the TPU probe"),
    }
    # the stand-in "kernel leg" number (>= 1.0 by construction): the
    # HBM model, NOT the A/A measurement — see honest_label
    paged_kernel["modeled_hbm_speedup"] = paged_kernel["attn_bytes_ratio"]
    # ISSUE 18 — quantized-KV bytes model: same deterministic position
    # counts, at the quantized storage's bytes/position (codes + the
    # amortized per-block scale plane). kv_quant_bytes_ratio is the
    # per-step f32/quant traffic ratio at EQUAL positions read — the
    # acceptance observable (>= 2x for int8); it composes with
    # attn_bytes_ratio (paging win x quant win = total vs gather-f32).
    qd = kv_dtype or os.environ.get("BENCH_SERVE_KV_DTYPE") or "int8"
    bpp_q = kv_bytes_per_position(qd)
    steps = max(1, kernel_on.get("decode_steps") or 1)
    paged_kernel["kv_dtype"] = qd
    paged_kernel["attn_bytes_per_step"]["kernel_quant"] = int(
        kernel_pos * bpp_q // steps)
    paged_kernel["kv_quant_bytes_ratio"] = round(
        _BYTES_PER_POSITION / bpp_q, 2)
    if kernel_on.get("tokens_s") and paged.get("tokens_s"):
        paged_kernel["scheduler_invariance_ratio"] = round(
            kernel_on["tokens_s"] / paged["tokens_s"], 2)
    rec = {
        "mode": "stub_churn",
        "block_size": _CHURN_BLOCK,
        "pool_positions": pool_positions,
        "slots_per_slot": slots_legacy,
        "slots_paged": slots_paged,
        "requests": n_requests,
        "per_slot": legs["per_slot"],
        "paged": paged,
        "paged_kernel": paged_kernel,
        # the ISSUE 11 acceptance observables, hoisted to the top level
        "kv_pool_utilization": paged.get("kv_pool_utilization"),
        "blocks_shared_frac": paged.get("blocks_shared_frac"),
        "blocks_shared_peak": paged.get("blocks_shared_peak"),
        "admission_block_waits": paged.get("admission_block_waits", 0),
        "preemptions": paged.get("preemptions", 0),
    }
    if legs["per_slot"].get("tokens_s") and legs["paged"].get("tokens_s"):
        rec["paged_speedup"] = round(
            legs["paged"]["tokens_s"] / legs["per_slot"]["tokens_s"], 2)
    return rec


# ---------------------------------------------------------------------------
# paged flash-decode kernel leg (ISSUE 15)
# ---------------------------------------------------------------------------

_PK_BLOCK = 16
_PK_MAX_LEN = 64
_PK_SLOTS = 4


def _run_paged_kernel_worker(n_requests: int) -> dict:
    """Inside the subprocess: the parent pinned
    ``SPARKDL_SERVE_PAGED_KERNEL`` BEFORE anything traced (the jit
    cache keys on traced shapes, not the knob — one process cannot
    measure both legs). Drives the churn mix through a small paged
    CPU-llama engine and returns the leg + sequential identity
    streams."""
    import jax

    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    workload = make_churn_workload(n_requests, vocab=cfg.vocab_size)

    def make_engine():
        return GenerationEngine.from_model(
            model, variables, num_slots=_PK_SLOTS, max_len=_PK_MAX_LEN,
            block_size=_PK_BLOCK, prefill_chunk=_PK_BLOCK,
            queue_capacity=max(64, n_requests))

    # identity streams: sequential fresh-engine drain — deterministic
    # scheduling, so the two workers' streams are directly comparable
    eng = make_engine()
    hs = [eng.submit(p, max_new_tokens=n) for p, n in workload[:6]]
    eng.run_until_idle()
    streams = [h.result(1) for h in hs]
    leg = run_engine_leg(make_engine, workload, concurrency=8)
    gather_pos, kernel_pos = attn_positions_model(
        workload, _PK_BLOCK, _PK_MAX_LEN)
    return {"leg": leg, "streams": streams,
            "attn_positions": {"gather_view": gather_pos,
                               "kernel": kernel_pos},
            "bytes_per_position":
                2 * cfg.num_kv_heads * cfg.head_dim * 4 * cfg.num_layers,
            "kv_heads": cfg.num_kv_heads, "head_dim": cfg.head_dim,
            "layers": cfg.num_layers,
            "kv_dtype": os.environ.get("SPARKDL_SERVE_KV_DTYPE", ""),
            "kernel_knob":
                os.environ.get("SPARKDL_SERVE_PAGED_KERNEL", "auto")}


def run_paged_kernel_comparison(n_requests: int = 12,
                                timeout_s: float = 300.0,
                                kv_dtype: str | None = None) -> dict:
    """ISSUE 15 CPU-llama kernel leg (healthy records): the paged
    engine with the kernel FORCED vs the gather view, one subprocess
    per knob value. On CPU the kernel runs through the Pallas
    interpreter, so this leg pins ENGAGEMENT + greedy token identity;
    the wall-clock comparison favors whichever path XLA compiles
    natively (honest label), and the HBM-bytes claim rides the
    deterministic attention-read model — the measured on-chip speedup
    is the next TPU probe's job."""
    import subprocess

    from sparkdl_tpu.serving.engine import scrub_serving_env

    legs = {}
    for name, env_val in (("kernel_on", "1"), ("kernel_off", "0")):
        env = dict(os.environ)
        scrub_serving_env(env)
        env["JAX_PLATFORMS"] = "cpu"
        env["SPARKDL_SERVE_PAGED_KERNEL"] = env_val
        if kv_dtype:
            # ISSUE 18 — both workers serve from the QUANTIZED pool, so
            # token_identical pins interpret-kernel == dequant-gather
            # at this dtype (the in-kernel dequant correctness pin).
            env["SPARKDL_SERVE_KV_DTYPE"] = kv_dtype
        args = [sys.executable, os.path.abspath(__file__),
                "--paged-kernel-worker", "--requests", str(n_requests)]
        out = subprocess.run(args, env=env, capture_output=True,
                             text=True, timeout=timeout_s)
        if out.returncode != 0:
            return {"mode": "llama_paged_kernel", "error":
                    (out.stderr or out.stdout or "")[-500:]}
        for line in reversed(out.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                legs[name] = json.loads(line)
                break
        else:
            return {"mode": "llama_paged_kernel",
                    "error": f"no JSON from {name} worker"}
    on, off = legs["kernel_on"], legs["kernel_off"]
    gp = on["attn_positions"]["gather_view"]
    kp = on["attn_positions"]["kernel"]
    bpp = on["bytes_per_position"]
    bpp_q = kv_bytes_per_position(
        kv_dtype, kv_heads=on.get("kv_heads", 4),
        head_dim=on.get("head_dim", 128),
        layers=on.get("layers", 2), block_size=_PK_BLOCK) \
        if kv_dtype else None
    rec = {
        "mode": "llama_paged_kernel",
        "block_size": _PK_BLOCK, "max_len": _PK_MAX_LEN,
        "num_slots": _PK_SLOTS, "requests": n_requests,
        "kv_dtype": kv_dtype or "float",
        "kernel_on": on["leg"], "kernel_off": off["leg"],
        "token_identical": on["streams"] == off["streams"],
        "attn_bytes": {"gather_view": gp * bpp, "kernel": kp * bpp,
                       "ratio": round(gp / kp, 2) if kp else None,
                       **({"kernel_quant": int(kp * bpp_q),
                           "kv_quant_bytes_ratio":
                               round(bpp / bpp_q, 2)}
                          if bpp_q else {})},
        "honest_label": (
            "CPU runs the kernel through the Pallas interpreter: this "
            "leg pins engagement + token identity; wall-clock favors "
            "the natively compiled gather on CPU — the HBM win "
            "(attn_bytes ratio) is measured on-chip"),
    }
    if on["leg"].get("tokens_s") and off["leg"].get("tokens_s"):
        rec["cpu_speedup"] = round(
            on["leg"]["tokens_s"] / off["leg"]["tokens_s"], 2)
    return rec


# ---------------------------------------------------------------------------
# speculative-decoding leg (ISSUE 12)
# ---------------------------------------------------------------------------

_SPEC_KS = (0, 2, 4)
_SPEC_CONCURRENCIES = (1, 8)
_SPEC_POOL = 4      # distinct prompts; repeats = retrieval-draft hits
_SPEC_PHRASE = 6    # prompt = a short phrase repeated (repetitive text)
_SPEC_PROMPT = 24
_SPEC_OUT = 64
_SPEC_MAX_LEN = 256
_SPEC_CHUNK = 16


def make_spec_workload(n: int, vocab: int, seed: int = 7,
                       n_new: int = _SPEC_OUT):
    """The high-acceptance mix speculation is measured on (ROADMAP
    item 2 scopes the ≥2× target to exactly this regime): a small pool
    of REPETITIVE prompts (a short phrase repeated — the
    prompt-lookup/self-drafting home turf) requested over and over
    (the FAQ/retry-storm class the main workload already models).
    Greedy decode is deterministic, so a repeat's whole stream is
    predicted token-for-token by the previous completion — retrieval
    drafting (``serving.draft.HistoryDraft``) turns that into near-k+1
    commits per verify window, and the batched verify is what makes
    the retrieved draft PROVEN output rather than a stale-cache
    answer."""
    rng = np.random.RandomState(seed)
    reps = -(-_SPEC_PROMPT // _SPEC_PHRASE)
    pool = [(rng.randint(0, vocab, _SPEC_PHRASE).tolist()
             * reps)[:_SPEC_PROMPT] for _ in range(_SPEC_POOL)]
    return [(pool[rng.randint(_SPEC_POOL)], n_new) for _ in range(n)]


def _spec_config():
    """Spec-leg model: NARROW on purpose. Speculative decoding attacks
    dispatch-bound sequential decode (one jitted dispatch per token per
    iteration — the ISSUE 12 floor): on TPU a decode step is
    memory/dispatch-bound, so a k+1-wide verify costs about one step.
    On CPU that regime holds only while per-step COMPUTE stays small
    against the ~ms per-call dispatch — the main serve leg's wide model
    (chosen so prefill compute dominates dispatch) would instead
    measure a compute-bound verify, which is not the economics
    speculation targets. h256×2 keeps the CPU leg dispatch-bound, i.e.
    TPU-decode-shaped."""
    from sparkdl_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=2,
                       num_heads=4, num_kv_heads=2,
                       intermediate_size=512, rope_theta=10000.0)


def _spec_record(legs: dict, ks, concurrencies) -> dict:
    """Headline ratios: single-stream (c=1) tokens/s of each k leg over
    the k=0 leg — the ROADMAP item 2 observable — plus the top-k leg's
    acceptance stats."""
    rec: dict = {"ks": list(ks), "concurrencies": list(concurrencies),
                 "legs": legs}
    base = legs.get("k0_c1") or {}
    top = legs.get(f"k{max(ks)}_c1") or {}
    if base.get("tokens_s") and top.get("tokens_s"):
        rec["spec_speedup"] = round(top["tokens_s"] / base["tokens_s"], 2)
        rec["spec_speedup_by_k"] = {
            str(k): round((legs.get(f"k{k}_c1") or {}).get("tokens_s", 0)
                          / base["tokens_s"], 2)
            for k in ks if k and legs.get(f"k{k}_c1", {}).get("tokens_s")}
    c_top = max(concurrencies)
    if c_top != 1:
        b8 = legs.get(f"k0_c{c_top}") or {}
        t8 = legs.get(f"k{max(ks)}_c{c_top}") or {}
        if b8.get("tokens_s") and t8.get("tokens_s"):
            rec[f"spec_speedup_c{c_top}"] = round(
                t8["tokens_s"] / b8["tokens_s"], 2)
    rec["spec_accept_rate"] = top.get("spec_accept_rate")
    rec["spec_mean_accept_len"] = top.get("spec_mean_accept_len")
    return rec


def run_spec_comparison_stub(n_requests: int = 32, num_slots: int = 4,
                             max_len: int = _SPEC_MAX_LEN,
                             ks=_SPEC_KS,
                             concurrencies=_SPEC_CONCURRENCIES,
                             step_s: float = 0.002,
                             spec_tok_s: float = 5e-5,
                             vocab: int = 8,
                             n_new: int = _SPEC_OUT) -> dict:
    """Jax-free speculative leg: the stub's deterministic token stream
    is arithmetic mod ``vocab``, so a SMALL vocab makes every output
    periodic (period = vocab) — repetitive text by construction, the
    n-gram DEFAULT provider's home turf (no retrieval corpus needed).
    ``verify`` costs one ``step_s`` + ``spec_tok_s``·k (the marginal
    verify-width device time), so the k-vs-0 ratio measures dispatch
    economics — tokens per program dispatch — which is the thing
    speculation buys on hardware."""
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    workload = make_spec_workload(n_requests, vocab, n_new=n_new)

    def make_engine(k: int):
        return GenerationEngine(
            StubBackend(num_slots, max_len, vocab_size=vocab,
                        step_s=step_s, spec_tok_s=spec_tok_s),
            queue_capacity=max(64, n_requests), prefill_chunk=8,
            spec_k=k)

    legs = {}
    outs = {}
    for k in ks:
        for c in concurrencies:
            leg = run_engine_leg(lambda k=k: make_engine(k), workload, c)
            legs[f"k{k}_c{c}"] = leg
    # identity: the stub stream is deterministic in the prompt, so the
    # spec and k=0 engines must emit identical tokens — proven inline
    # on a fresh engine pair (drained, single-threaded).
    for k in (0, max(ks)):
        eng = make_engine(k)
        hs = [eng.submit(p, max_new_tokens=n) for p, n in workload[:6]]
        eng.run_until_idle()
        outs[k] = [h.result(1) for h in hs]
    rec = {"mode": "stub_spec", "step_s": step_s,
           "spec_tok_s": spec_tok_s, "vocab": vocab,
           "num_slots": num_slots, "requests": n_requests,
           **_spec_record(legs, ks, concurrencies)}
    rec["spec_token_identical"] = outs[0] == outs[max(ks)]
    return rec


def run_spec_comparison_llama(n_requests: int = 48, num_slots: int = 2,
                              max_len: int = _SPEC_MAX_LEN,
                              ks=_SPEC_KS,
                              concurrencies=_SPEC_CONCURRENCIES) -> dict:
    """CPU-llama speculative leg (the ROADMAP item 2 acceptance
    number): single-stream and c=8 runs at k∈{0,2,4} on the
    dispatch-bound spec model over the high-acceptance retry-storm
    mix, drafting via ``HistoryDraft`` (retrieval + prompt-lookup
    fallback). Greedy output is spot-checked token-identical between
    the k=0 and speculative engines, and the verify program's
    compile-cache signatures pin zero re-traces across the measured
    legs."""
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine
    from sparkdl_tpu.serving.draft import HistoryDraft

    cfg = _spec_config()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(1),
                           np.zeros((1, 4), np.int32))
    workload = make_spec_workload(n_requests, cfg.vocab_size)

    def make_engine(k: int):
        return GenerationEngine.from_model(
            model, variables, num_slots=num_slots, max_len=max_len,
            min_bucket=_MIN_BUCKET, queue_capacity=max(64, n_requests),
            prefill_chunk=_SPEC_CHUNK, spec_k=k,
            draft_provider=HistoryDraft() if k else None)

    # warmup: compile every program each k-leg uses (chunk + decode +
    # one verify program per k), then pin the signature set
    outs = {}
    for k in ks:
        eng = make_engine(k)
        hs = [eng.submit(p, max_new_tokens=8) for p, _ in workload[:4]]
        eng.run_until_idle()
        outs[k] = [h.result(1) for h in hs]
    identical = all(outs[k] == outs[0] for k in ks)
    sig_verify = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
    sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

    legs = {}
    for k in ks:
        for c in concurrencies:
            leg = run_engine_leg(lambda k=k: make_engine(k), workload, c)
            legs[f"k{k}_c{c}"] = leg

    rec = {
        "mode": "llama_spec",
        "platform": jax.default_backend(),
        "model": {"vocab_size": cfg.vocab_size,
                  "hidden_size": cfg.hidden_size,
                  "num_layers": cfg.num_layers},
        "num_slots": num_slots, "max_len": max_len,
        "prefill_chunk": _SPEC_CHUNK, "requests": n_requests,
        "draft_provider": "history",
        **_spec_record(legs, ks, concurrencies),
    }
    rec["spec_token_identical"] = identical
    rec["verify_retrace_after_warmup"] = (
        GLOBAL_COMPILE_CACHE.signatures("serve_verify_step") - sig_verify)
    rec["decode_retrace_after_warmup"] = (
        GLOBAL_COMPILE_CACHE.signatures("serve_decode_step") - sig_decode)
    return rec


# ---------------------------------------------------------------------------
# tensor-parallel leg (ISSUE 14)
# ---------------------------------------------------------------------------

_TP_DEGREES = (1, 2, 4)
_TP_HONEST_LABEL = (
    "8 virtual CPU devices: validates multi-chip SEMANTICS (token "
    "identity, zero re-traces, 1/tp per-device KV bytes) and re-trace/"
    "memory economics — NOT wall-clock speedup; ICI-real tokens/s "
    "needs the TPU backend")


def _tp_config():
    """TP-leg model: tiny (the leg measures semantics, not throughput —
    see the honest label) with num_kv_heads == 4 so the head-sharded
    KV layout is exact at every measured degree (tp must divide the KV
    head count)."""
    from sparkdl_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=512, hidden_size=128, num_layers=2,
                       num_heads=4, num_kv_heads=4,
                       intermediate_size=256, rope_theta=10000.0)


def make_tp_workload(n: int, vocab: int, seed: int = 11):
    """Composition mix for the tp identity drive: every prompt opens
    with a shared 16-token head (2 radix blocks at block_size 8 — the
    graft path), bodies are short repeated phrases (the n-gram
    self-drafting regime, so the speculative verify path runs on
    real drafts), outputs 8."""
    rng = np.random.RandomState(seed)
    head = rng.randint(0, vocab, 16).tolist()
    phrases = [rng.randint(0, vocab, 4).tolist() for _ in range(4)]
    out = []
    for _ in range(n):
        body = (phrases[rng.randint(len(phrases))] * 3)[:rng.randint(3, 12)]
        out.append((head + body, 8))
    return out


def _run_tp_worker(degrees, n_requests: int) -> dict:
    """The in-subprocess half of the tp leg (the parent spawned us with
    XLA_FLAGS forcing 8 virtual CPU devices — jax must not have
    initialized a backend before this runs): for each tp degree, the
    SAME paged + chunked-prefill + speculative engine config over the
    same workload — greedy streams must be identical across degrees,
    decode/verify must never re-trace after warmup, and per-device KV
    pool bytes must shrink to ~1/tp."""
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    cfg = _tp_config()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    workload = make_tp_workload(n_requests, cfg.vocab_size)
    degrees = [d for d in degrees if d <= len(jax.devices())]

    def make_engine(tp: int):
        return GenerationEngine.from_model(
            model, variables, num_slots=4, max_len=64, prefill_chunk=8,
            block_size=8, prefill_budget=16, spec_k=3, tp=tp,
            queue_capacity=max(64, n_requests))

    rec: dict = {"mode": "tp", "n_devices": len(jax.devices()),
                 "platform": jax.default_backend(),
                 "honest_label": _TP_HONEST_LABEL,
                 "degrees": {}, "requests": n_requests}
    streams: dict = {}
    for tp in degrees:
        # identity drive: sequential (drained) — per-request streams
        # are scheduler-order-free evidence
        eng = make_engine(tp)
        hs = [eng.submit(p, max_new_tokens=n) for p, n in workload[:8]]
        eng.run_until_idle()
        streams[tp] = [h.result(1) for h in hs]
        sig_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
        sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
        leg = run_engine_leg(lambda tp=tp: make_engine(tp),
                             workload, concurrency=4)
        leg["kv_pool_device_bytes"] = eng.kv_pool_device_bytes
        leg["tp_degree"] = tp
        leg["decode_retrace_after_warmup"] = (
            GLOBAL_COMPILE_CACHE.signatures("serve_decode_step") - sig_d)
        leg["verify_retrace_after_warmup"] = (
            GLOBAL_COMPILE_CACHE.signatures("serve_verify_step") - sig_v)
        rec["degrees"][str(tp)] = leg
    # anchor on the first MEASURED degree (a BENCH_TP_DEGREES without
    # tp=1 must still record cross-degree identity, not drop it); ONE
    # measured degree is no cross-degree evidence at all — report None,
    # never a vacuous True (an operator-pinned device_count=1 flag can
    # filter the list down to a single degree)
    rec["measured_degrees"] = list(degrees)
    if len(streams) >= 2:
        base = streams[degrees[0]]
        rec["tp_identical"] = all(s == base for s in streams.values())
    else:
        rec["tp_identical"] = None
    rec["kv_pool_device_bytes"] = {
        str(tp): rec["degrees"][str(tp)]["kv_pool_device_bytes"]
        for tp in degrees}
    b1 = rec["kv_pool_device_bytes"].get("1")
    if b1:
        rec["kv_pool_device_frac"] = {
            str(tp): round(rec["kv_pool_device_bytes"][str(tp)] / b1, 4)
            for tp in degrees}
    return rec


def run_tp_comparison(n_requests: int = 24,
                      degrees=_TP_DEGREES,
                      timeout_s: float = 900.0) -> dict:
    """ISSUE 14 tp leg — ALWAYS a fresh subprocess: the 8-virtual-device
    CPU mesh must be forced before jax initializes a backend, which the
    parent (possibly already holding a TPU or a 1-device CPU backend)
    cannot do in-process. Runs in both healthy and backend_unavailable
    bench records (the never-host-blind rule): the semantics it proves
    are device-count economics, not wall-clock."""
    import subprocess

    from sparkdl_tpu.runner.launcher import host_device_flags
    env = dict(os.environ)
    env["XLA_FLAGS"] = host_device_flags(env.get("XLA_FLAGS", ""), 8)
    env["JAX_PLATFORMS"] = "cpu"
    # Evidence hygiene (shared with tp_serving_record.py and the
    # dryrun leg): ambient serving knobs must not reshape the leg —
    # see scrub_serving_env's docstring for why KV_POOL_MB in
    # particular would invert the 1/tp observable.
    from sparkdl_tpu.serving.engine import scrub_serving_env
    scrub_serving_env(env)
    args = [sys.executable, os.path.abspath(__file__), "--tp-worker",
            "--requests", str(n_requests),
            "--degrees", ",".join(str(d) for d in degrees)]
    out = subprocess.run(args, env=env, capture_output=True, text=True,
                         timeout=timeout_s)
    if out.returncode != 0:
        return {"mode": "tp", "error":
                (out.stderr or out.stdout or "")[-500:]}
    # last line of stdout is the JSON record (warnings may precede it)
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return {"mode": "tp", "error": "no JSON in tp worker output"}


# ---------------------------------------------------------------------------
# ISSUE 19 survivability leg (stub, jax-free — rides BOTH records)
# ---------------------------------------------------------------------------

def run_survivability_comparison(n_requests: int = 24,
                                 num_slots: int = 4,
                                 concurrency: int = 8,
                                 step_s: float = 0.002) -> dict:
    """The serving-survivability cost model: the SAME closed-loop
    workload driven clean and with ONE injected ``cache_lost`` failover
    mid-decode (seeded chaos plan, fires once). Reports tokens/s and
    TTFT p99 for both runs, the failover recovery latency (fault to
    first resumed token, off the engine's own ledger), and whether the
    faulted run's greedy streams were token-identical to the clean
    run's — the exactly-once resume observable ``bench_trend`` gates
    (``serve_recovery_s`` lower-is-better, and
    ``serve_failover_token_identical`` must stay 1.0)."""
    from sparkdl_tpu.runner import chaos, telemetry
    from sparkdl_tpu.runner.chaos import Fault, FaultPlan
    from sparkdl_tpu.runner.telemetry import histogram_quantile
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    vocab = 997  # prime: the stub fold-chain stream is a real oracle
    rng = np.random.RandomState(5)
    workload = [(rng.randint(1, vocab,
                             size=int(rng.choice((4, 8, 16)))).tolist(),
                 int(rng.choice((8, 16)))) for _ in range(n_requests)]

    def drive(plan):
        chaos.uninstall()
        telemetry.reset()
        telemetry.start()
        # fixed backoff dominates recovery_s so the gated number is a
        # stable ~50ms+resume figure, not sub-millisecond timer noise
        eng = GenerationEngine(
            StubBackend(num_slots, 256, vocab_size=vocab,
                        step_s=step_s), retries=1,
            failover_backoff_s=0.05)
        if plan is not None:
            chaos.install(plan)
        tokens_by_idx: dict = {}
        errors: list = []

        def client(idx_chunk):
            try:
                for i in idx_chunk:
                    prompt, new = workload[i]
                    h = eng.submit(prompt, max_new_tokens=new)
                    tokens_by_idx[i] = h.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — recorded below
                errors.append(f"{type(e).__name__}: {e}")

        chunks = [list(range(len(workload)))[i::concurrency]
                  for i in range(concurrency)]
        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True) for c in chunks if c]
        eng.start()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        wall = time.perf_counter() - t0
        eng.stop(drain=True, timeout=30)
        ttft = telemetry.registry().histogram("serving_ttft_s").snapshot()
        snap = eng.snapshot()
        try:
            chaos.uninstall()
        finally:
            telemetry.reset()
        total = sum(len(v) for v in tokens_by_idx.values())
        leg = {"completed": snap["completed"],
               "tokens": total,
               "wall_s": round(wall, 4),
               "tokens_s": round(total / wall, 2) if wall > 0 else None,
               "ttft_p99_s": histogram_quantile(ttft, 0.99),
               "failovers": snap["failovers"],
               "failover_resumed": snap["failover_resumed"],
               "recovery_s": snap["failover"].get("last_recovery_s")}
        if errors:
            leg["errors"] = errors[:5]
        return leg, tokens_by_idx

    clean, clean_toks = drive(None)
    # seeded prob + once: fires exactly one cache_lost on SOME decode
    # call a little into the run — deterministic for a given seed
    faulted, fault_toks = drive(FaultPlan(
        [Fault("serve_decode", "cache_lost", prob=0.2)], seed=9))
    identical = (set(clean_toks) == set(fault_toks) and all(
        clean_toks[i] == fault_toks[i] for i in clean_toks))
    return {
        "requests": n_requests, "concurrency": concurrency,
        "num_slots": num_slots, "step_s": step_s,
        "clean": clean, "faulted": faulted,
        "failovers": faulted["failovers"],
        "recovery_s": faulted["recovery_s"],
        # float on purpose: bench_trend auto-gates numeric scalars and
        # skips bools — 1.0 means every stream matched the clean run
        "token_identical": 1.0 if identical else 0.0,
        "tokens_s_ratio": round(
            faulted["tokens_s"] / clean["tokens_s"], 4)
        if clean["tokens_s"] and faulted["tokens_s"] else None,
    }


def run_fleet_comparison(n_requests: int = 24, n_replicas: int = 3,
                         num_slots: int = 2,
                         step_s: float = 0.002) -> dict:
    """The fleet-tier cost model (ISSUE 20), two sub-legs on the stub:

    **Routing** — the SAME prefix-family burst workload through a
    radix-routed fleet and the round-robin comparator, overloaded
    (more concurrent clients than fleet slots): fleet-wide prefix
    reuse/hit-rate and TTFT p99 per policy. Radix must not lose — the
    co-location win is the whole point of shadow-residency routing.

    **Recovery** — an inline fleet run with one unclean replica kill
    mid-stream: ``fleet_recovery_s`` is kill-to-first-re-admitted-token
    (bench_trend auto-gates it lower-is-better) and
    ``fleet_token_identical`` (float; must stay 1.0) is the
    zero-dup/zero-loss delivery-cursor + greedy-identity gate against a
    clean single-engine run."""
    from sparkdl_tpu.runner import telemetry
    from sparkdl_tpu.runner.telemetry import histogram_quantile
    from sparkdl_tpu.serving import (EngineFleet, GenerationEngine,
                                     StubBackend)

    vocab = 997
    rng = np.random.RandomState(11)
    families = [rng.randint(1, vocab, size=48).tolist()
                for _ in range(n_replicas)]
    workload = []
    per_family = max(4, n_requests // len(families))
    for fi, head in enumerate(families):  # burst arrival per family
        for i in range(per_family):
            workload.append((head + [500 + 10 * fi + i], 8))

    def mk():
        return GenerationEngine(
            StubBackend(num_slots, 96, vocab_size=vocab, step_s=step_s,
                        prefix_cache_bytes=1 << 20), retries=1)

    def routing_leg(routing):
        telemetry.reset()
        telemetry.start()
        fleet = EngineFleet([mk() for _ in range(n_replicas)],
                            routing=routing)
        done: dict = {}
        errors: list = []

        def client(idx_chunk):
            try:
                for i in idx_chunk:
                    prompt, new = workload[i]
                    h = fleet.submit(prompt, max_new_tokens=new)
                    done[i] = h.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — recorded below
                errors.append(f"{type(e).__name__}: {e}")

        concurrency = 2 * n_replicas * num_slots  # genuine overload
        chunks = [list(range(len(workload)))[i::concurrency]
                  for i in range(concurrency)]
        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True) for c in chunks if c]
        fleet.start()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        wall = time.perf_counter() - t0
        fleet.stop(drain=True, timeout=30)
        ttft = telemetry.registry().histogram("serving_ttft_s").snapshot()
        reused = hits = misses = 0
        for name in fleet.replica_names():
            ps = fleet.engine(name).backend.prefix_stats() or {}
            reused += ps.get("reused_tokens", 0)
            hits += ps.get("hits", 0)
            misses += ps.get("misses", 0)
        telemetry.reset()
        total = sum(len(v) for v in done.values())
        leg = {"completed": len(done), "tokens": total,
               "wall_s": round(wall, 4),
               "tokens_s": round(total / wall, 2) if wall > 0 else None,
               "ttft_p99_s": histogram_quantile(ttft, 0.99),
               "reused_tokens": reused,
               "hit_rate": round(hits / (hits + misses), 4)
               if hits + misses else None}
        if errors:
            leg["errors"] = errors[:5]
        return leg

    radix = routing_leg("radix")
    rr = routing_leg("round_robin")

    # recovery sub-leg: inline (deterministic service order → a real
    # token-identity oracle), one unclean kill mid-stream
    clean_eng = mk()
    clean = [clean_eng.submit(p, max_new_tokens=n, block=False)
             for p, n in workload]
    clean_eng.run_until_idle()

    fleet = EngineFleet([mk() for _ in range(n_replicas)])
    t_kill = t_readmit = None

    def cb(fr, tok):
        nonlocal t_readmit
        if t_kill is not None and t_readmit is None and fr.hops > 0:
            t_readmit = time.perf_counter()

    frs = [fleet.submit(p, max_new_tokens=n, stream_cb=cb)
           for p, n in workload]
    for _ in range(4):
        fleet.step()
    victim = next(fr.replica for fr in frs
                  if not fr.done and fr.replica is not None)
    t_kill = time.perf_counter()
    fleet.kill_replica(victim)
    fleet.run_until_idle()
    recovery_s = round(t_readmit - t_kill, 4) if t_readmit else None
    identical = all(fr.state == "done" and fr.tokens == c.tokens
                    and fr.delivered == len(fr.tokens)
                    for fr, c in zip(frs, clean))
    return {
        "requests": len(workload), "replicas": n_replicas,
        "num_slots": num_slots, "step_s": step_s,
        "radix": radix, "round_robin": rr,
        "reuse_ratio": round(radix["reused_tokens"]
                             / rr["reused_tokens"], 4)
        if rr["reused_tokens"] else None,
        "readmissions": fleet.stats["readmissions"],
        # the two bench_trend-gated scalars (float on purpose — the
        # trend gate skips bools; _s suffix = auto lower-is-better)
        "recovery_s": recovery_s,
        "token_identical": 1.0 if identical else 0.0,
    }


def run_stub_scheduler_comparison(n_requests: int = 96,
                                  num_slots: int = 8,
                                  step_s: float = 0.002,
                                  prefill_tok_s: float = 2e-4) -> dict:
    """The regression pin (test_bench rides this): stall-free vs
    blocking on the long-prompt mix with deterministic synthetic device
    costs — returns both top-concurrency legs + ratios, so the
    scheduler win stays pinned without hardware (the test asserts
    conservative floors under the bench-record targets: 1.2x tokens/s,
    1.2x TTFT p99, 2.5x decode stall)."""
    return _run_stub(n_requests, num_slots, _DEF_MAX_LEN, (16,),
                     step_s, prefill_tok_s)


def run(mode: str = "llama", rows: int | None = None) -> dict:
    """Bench entry point (``bench.py --worker serve`` / ``serve_stub``).
    Env knobs: BENCH_SERVE_REQUESTS / _SLOTS / _MAX_LEN /
    _CONCURRENCY (comma list) / _CHUNK / _STUB_STEP_S /
    _STUB_PREFILL_TOK_S."""
    n = rows or int(os.environ.get("BENCH_SERVE_REQUESTS", _DEF_REQUESTS))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", _DEF_SLOTS))
    max_len = int(os.environ.get("BENCH_SERVE_MAX_LEN", _DEF_MAX_LEN))
    conc = tuple(int(c) for c in os.environ.get(
        "BENCH_SERVE_CONCURRENCY", "1,8,32").split(",") if c)
    if mode == "stub":
        step_s = float(os.environ.get("BENCH_SERVE_STUB_STEP_S", "0.002"))
        tok_s = float(os.environ.get("BENCH_SERVE_STUB_PREFILL_TOK_S",
                                     "2e-4"))
        rec = _run_stub(n, slots, max_len, conc, step_s, tok_s)
    else:
        rec = _run_llama(n, slots, max_len, conc)
    # ISSUE 11 high-churn paged-vs-per-slot leg: a memory/scheduling
    # property, measured jax-free on the stub (seconds of wall) so it
    # rides BOTH the healthy llama record and the outage stub record.
    if not os.environ.get("BENCH_SKIP_CHURN"):
        try:
            rec["churn"] = run_paged_churn_comparison(
                n_requests=min(192, max(64, n)))
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["churn_error"] = f"{type(e).__name__}: {e}"[:300]
    # ISSUE 12 speculative-decoding leg: single-stream + c=8 at
    # k∈{0,2,4}. The llama record carries the real-model CPU leg (the
    # ROADMAP ≥2× single-stream target); the stub record carries the
    # jax-free scheduler leg — so healthy AND backend_unavailable
    # records both hold a speculation number (never-host-blind rule).
    if not os.environ.get("BENCH_SKIP_SPEC"):
        try:
            rec["spec"] = run_spec_comparison_stub(
                n_requests=min(32, max(16, n))) if mode == "stub" \
                else run_spec_comparison_llama(
                    n_requests=min(48, max(16, n)))
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["spec_error"] = f"{type(e).__name__}: {e}"[:300]
    # ISSUE 19 survivability leg: one injected failover vs clean on the
    # stub (jax-free, seconds of wall) — recovery latency and the
    # exactly-once token-identity gate ride BOTH the healthy llama
    # record and the backend_unavailable stub record, so an outage
    # never blinds the survivability trend.
    if not os.environ.get("BENCH_SKIP_SURVIVABILITY"):
        try:
            rec["survivability"] = run_survivability_comparison(
                n_requests=min(24, max(12, n)))
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["survivability_error"] = f"{type(e).__name__}: {e}"[:300]
    # ISSUE 20 fleet leg: radix-vs-round-robin routing under overload
    # plus one unclean replica kill with the cross-replica exactly-once
    # gate — jax-free on the stub, so fleet recovery and routing trends
    # ride BOTH the healthy llama record and the backend_unavailable
    # stub record (never-host-blind).
    if not os.environ.get("BENCH_SKIP_FLEET"):
        try:
            rec["fleet"] = run_fleet_comparison(
                n_requests=min(24, max(12, n)))
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["fleet_error"] = f"{type(e).__name__}: {e}"[:300]
    # ISSUE 15 paged-kernel leg (real model, llama records only — the
    # stub record's kernel evidence is the churn sub-leg above): two
    # subprocesses pin kernel-on vs gather-view token identity + the
    # attention-bytes model.
    if mode != "stub" and not os.environ.get("BENCH_SKIP_PAGED_KERNEL"):
        try:
            rec["paged_kernel"] = run_paged_kernel_comparison(
                n_requests=int(os.environ.get("BENCH_PAGED_KERNEL_REQUESTS",
                                              "12")),
                kv_dtype=os.environ.get("BENCH_SERVE_KV_DTYPE") or None)
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["paged_kernel_error"] = f"{type(e).__name__}: {e}"[:300]
    # ISSUE 14 tensor-parallel leg: a fresh subprocess on the forced
    # 8-virtual-device CPU mesh (tp in {1,2,4}) — identity, re-trace
    # and per-device-KV-bytes semantics ride BOTH the healthy llama
    # record and the outage stub record (never-host-blind; the honest
    # label in the leg states what virtual devices do NOT measure).
    if not os.environ.get("BENCH_SKIP_TP"):
        try:
            rec["tp"] = run_tp_comparison(
                n_requests=int(os.environ.get("BENCH_TP_REQUESTS", "24")),
                degrees=tuple(int(d) for d in os.environ.get(
                    "BENCH_TP_DEGREES", "1,2,4").split(",") if d))
        except Exception as e:  # noqa: BLE001 — the main legs stand
            rec["tp_error"] = f"{type(e).__name__}: {e}"[:300]
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stub", action="store_true",
                    help="jax-free scheduler-only run (StubBackend)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--tp", action="store_true",
                    help="tensor-parallel leg only (spawns the "
                         "8-virtual-device subprocess)")
    ap.add_argument("--tp-worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: inside the
    # forced-virtual-device subprocess run_tp_comparison spawned
    ap.add_argument("--degrees", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--paged-kernel-worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one knob value
    # per process (run_paged_kernel_comparison spawned us)
    ap.add_argument("--kv-dtype", default=None,
                    choices=("float", "int8", "fp8"),
                    help="KV pool storage dtype for the paged legs "
                         "(ISSUE 18): the churn leg's quant bytes "
                         "model uses it, and the real-model paged-"
                         "kernel leg serves from a pool quantized to "
                         "it (token identity pinned through the "
                         "in-kernel dequant)")
    ns = ap.parse_args(argv)
    if ns.kv_dtype and ns.kv_dtype != "float":
        os.environ["BENCH_SERVE_KV_DTYPE"] = ns.kv_dtype
    if ns.paged_kernel_worker:
        import jax
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_run_paged_kernel_worker(ns.requests or 16)))
        return 0
    if ns.tp_worker:
        # The parent set XLA_FLAGS/JAX_PLATFORMS in our env; latch the
        # platform before any backend initializes (the sitecustomize
        # pre-imports jax, so go through jax.config like conftest.py).
        import jax
        jax.config.update("jax_platforms", "cpu")
        degrees = tuple(int(d) for d in (ns.degrees or "1,2,4").split(",")
                        if d)
        rec = _run_tp_worker(degrees, ns.requests or 24)
        print(json.dumps(rec))  # one line — the parent parses the tail
        return 0
    if ns.tp:
        print(json.dumps(run_tp_comparison(
            n_requests=ns.requests or 24), indent=2))
        return 0
    if not ns.stub:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rec = run(mode="stub" if ns.stub else "llama", rows=ns.requests)
    print(json.dumps(rec, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
