#!/usr/bin/env python
"""Serving throughput bench — continuous batching vs the static
whole-batch path (ISSUE 8 acceptance evidence).

Workload: ``BENCH_SERVE_REQUESTS`` requests with mixed prompt lengths
and a long-tail output-length mix — the traffic shape continuous
batching wins on, because a static batch runs every row until the
LONGEST request in the batch finishes while in-flight batching retires
and refills each slot individually.

Three measurements per run:

- **engine legs** at closed-loop client concurrency 1 / 8 / 32 (each
  client submits one request and waits for its result — concurrency 1
  is the single-stream number, 32 saturates the slot table and builds a
  visible queue). Aggregate tokens/s plus request-latency and TTFT
  percentiles, derived from the telemetry plane's cumulative-bucket
  histograms via ``telemetry.histogram_quantile`` — the same helper
  ``bottleneck_report`` uses.
- **static comparator**: the same requests in arrival order, grouped
  into ``num_slots``-sized whole batches through
  ``models.llama.generate`` (one left-padded prefill + one decode
  program, each batch decoding max(out_lens) steps) — the pre-ISSUE-8
  serving shape with the same cache budget.
- **re-trace pin**: ``GLOBAL_COMPILE_CACHE.signatures()`` for the slot
  prefill / decode-step programs, captured after warmup and after the
  measured run — ``decode_retrace_after_warmup`` must be 0 (the
  compiled decode step is never re-traced by refills).

``mode="stub"`` swaps the model for the jax-free
``serving.StubBackend`` with a synthetic per-call device time and
*walks the static schedule with the same stub timings* — scheduler
throughput and the batching win stay measurable inside a
``backend_unavailable`` bench record (the never-host-blind rule from
the host-ingest leg).

Standalone:  JAX_PLATFORMS=cpu python scripts/serve_bench.py [--stub]
"""

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

_DEF_REQUESTS = 288
_DEF_SLOTS = 24
_DEF_MAX_LEN = 256
_PROMPT_LENS = (3, 6, 12, 24)
# Long-tail output mix: most requests are short, 1-in-16 wants 192
# tokens. A static 24-row batch then usually carries >= 1 long request
# and decodes ~192 steps for a ~17-token mean — exactly the whole-batch
# waste in-flight batching removes (pay mean steps, not max).
_OUT_CHOICES = (4, 6, 8, 192)
_OUT_PROBS = (0.45, 0.3, 0.1875, 0.0625)
_PAD_TO_COL = 32   # static path: one prompt-column width for all batches
_MIN_BUCKET = 8


def make_workload(n: int, vocab: int, seed: int = 0):
    """(prompt_ids, max_new_tokens) pairs with the long-tail output mix
    (mean ≈ 17 tokens, max 192 — a static ``num_slots``-batch of 24
    usually carries >= 1 long request and pays its full decode
    length)."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        plen = int(rng.choice(_PROMPT_LENS))
        new = int(rng.choice(_OUT_CHOICES, p=_OUT_PROBS))
        out.append((rng.randint(0, vocab, size=plen).tolist(), new))
    return out


def _quantiles(hist_snap):
    from sparkdl_tpu.runner.telemetry import histogram_quantile
    return {f"p{int(q * 100)}": histogram_quantile(hist_snap, q)
            for q in (0.5, 0.95, 0.99)}


def run_engine_leg(make_engine, workload, concurrency: int,
                   timeout_s: float = 600.0) -> dict:
    """Drive the workload through a fresh engine with ``concurrency``
    closed-loop clients; returns tokens/s + latency percentiles."""
    from sparkdl_tpu.runner import telemetry
    telemetry.reset()
    telemetry.start()  # registry-only plane: histograms for percentiles
    eng = make_engine()
    handles: list = []
    hlock = threading.Lock()
    errors: list = []

    def client(chunk):
        try:
            for prompt, new in chunk:
                h = eng.submit(prompt, max_new_tokens=new)
                with hlock:
                    handles.append(h)
                h.result(timeout=timeout_s)  # closed loop: wait, then next
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            errors.append(f"{type(e).__name__}: {e}")

    chunks = [workload[i::concurrency] for i in range(concurrency)]
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in chunks if c]
    eng.start()
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s)
    wall = time.perf_counter() - t0
    eng.stop(drain=True, timeout=30)
    tokens = sum(len(h.tokens) for h in handles)
    reg = telemetry.registry()
    lat = reg.histogram("serving_request_latency_s").snapshot()
    ttft = reg.histogram("serving_ttft_s").snapshot()
    snap = eng.snapshot()
    telemetry.reset()
    rec = {
        "concurrency": concurrency,
        "requests": len(handles),
        "completed": snap["completed"],
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
        "latency_s": _quantiles(lat),
        "ttft_s": _quantiles(ttft),
        "peak_queue_depth": snap["peak_queue_depth"],
        "peak_slots_busy": snap["peak_slots_busy"],
        "decode_steps": snap["steps"],
    }
    if errors:
        rec["errors"] = errors[:5]
    return rec


# ---------------------------------------------------------------------------
# llama mode (real model — CPU or TPU, whatever the ambient platform is)
# ---------------------------------------------------------------------------

def _bench_config():
    """The serving-bench model: big enough that one decode step's
    compute dominates per-step dispatch overhead (on CPU the tiny test
    config spends as long in Python/dispatch as in the matmuls, which
    would understate the batching win AND overstate it once real
    hardware makes dispatch relatively cheaper), small enough to stay
    inside a bench leg's budget everywhere."""
    from sparkdl_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=4,
                       num_heads=8, num_kv_heads=4, intermediate_size=512,
                       rope_theta=10000.0)


def _run_llama(n_requests: int, num_slots: int, max_len: int,
               concurrencies) -> dict:
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    cfg = _bench_config()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    workload = make_workload(n_requests, cfg.vocab_size)

    def make_engine():
        return GenerationEngine.from_model(
            model, variables, num_slots=num_slots, max_len=max_len,
            min_bucket=_MIN_BUCKET, queue_capacity=max(64, n_requests))

    # Greedy continuous batching must be token-identical to the static
    # path — spot-check a few requests against generate() FIRST (its
    # small private engine compiles a 2-slot decode program that must
    # not count against the re-trace pin below).
    spot_ok = _spot_check(model, variables, workload[:4], max_len)

    # -- warmup: compile every program both paths will use ----------------
    eng = make_engine()
    for plen in _PROMPT_LENS:  # one refill per prompt-length bucket
        eng.submit(list(range(1, 1 + plen)), max_new_tokens=2)
    eng.run_until_idle()
    # static path: one (batch, pad) prefill + one decode program per
    # distinct group-max output length
    for n_new in sorted(set(_OUT_CHOICES)):
        _static_pass(model, variables,
                     [([1, 2, 3], n_new)] * num_slots, num_slots, max_len)
    sig_prefill = GLOBAL_COMPILE_CACHE.signatures("serve_prefill")
    sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

    # -- engine legs ------------------------------------------------------
    # Closed-loop clients: low concurrency can't keep the slot table
    # full, so a c=1 leg over the whole workload would run for minutes
    # serving one slot — scale the request count with the offered load
    # (tokens/s normalizes it away; the FULL workload runs at max
    # concurrency, which is the headline + comparator leg).
    legs = {}
    for c in concurrencies:
        n_leg = len(workload) if c >= max(concurrencies) else \
            max(24, min(len(workload), c * 12))
        legs[str(c)] = run_engine_leg(make_engine, workload[:n_leg], c)

    # -- static whole-batch comparator ------------------------------------
    static = _static_pass(model, variables, workload, num_slots, max_len)

    retrace = (GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
               - sig_decode)
    rec = {
        "mode": "llama",
        "model": {"vocab_size": cfg.vocab_size,
                  "hidden_size": cfg.hidden_size,
                  "num_layers": cfg.num_layers,
                  "num_heads": cfg.num_heads,
                  "num_kv_heads": cfg.num_kv_heads,
                  "intermediate_size": cfg.intermediate_size},
        "platform": jax.default_backend(),
        "num_slots": num_slots,
        "max_len": max_len,
        "requests": n_requests,
        "engine": legs,
        "static": static,
        "prefill_buckets_compiled": sig_prefill,
        "decode_retrace_after_warmup": retrace,
        "decode_signatures": GLOBAL_COMPILE_CACHE.signatures(
            "serve_decode_step"),
    }
    top = legs.get(str(max(concurrencies)), {})
    if top.get("tokens_s") and static.get("tokens_s"):
        rec["speedup_vs_static"] = round(
            top["tokens_s"] / static["tokens_s"], 2)
    rec["token_identical_spot_check"] = spot_ok
    return rec


def _static_pass(model, variables, workload, batch: int,
                 max_len: int) -> dict:
    """The pre-ISSUE-8 serving shape: whole batches in arrival order;
    every batch decodes max(out_lens) steps (EOS-free greedy — rows that
    finished their requested length keep decoding until the longest row
    is done, exactly the waste continuous batching removes). Short tail
    batches are padded to the full batch width by repeating the last
    request so one (batch, pad) program serves every group; only
    requested tokens count."""
    from sparkdl_tpu.models import llama as L
    lat: list[float] = []
    tokens = 0
    t0 = time.perf_counter()
    for i in range(0, len(workload), batch):
        grp = list(workload[i:i + batch])
        real = len(grp)
        while len(grp) < batch:
            grp.append(grp[-1])
        prompts = [p for p, _ in grp]
        outs = [n for _, n in grp]
        ids, lens = L.left_pad_prompts(prompts, pad_to=_PAD_TO_COL)
        out = L.generate(model, variables, np.asarray(ids),
                         int(max(outs)), pad_lens=np.asarray(lens),
                         pad_to=max_len)
        np.asarray(out)  # host fetch = the timing barrier
        done = time.perf_counter() - t0
        tokens += sum(outs[:real])
        lat.extend([done] * real)  # all requests arrived at t0
    wall = time.perf_counter() - t0
    lat_arr = np.asarray(lat) if lat else np.asarray([0.0])
    return {
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
        "batches": -(-len(workload) // batch),
        "latency_s": {"p50": round(float(np.percentile(lat_arr, 50)), 6),
                      "p95": round(float(np.percentile(lat_arr, 95)), 6),
                      "p99": round(float(np.percentile(lat_arr, 99)), 6)},
    }


def _spot_check(model, variables, pairs, max_len: int) -> bool:
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine
    eng = GenerationEngine.from_model(model, variables, num_slots=2,
                                      max_len=max_len,
                                      min_bucket=_MIN_BUCKET)
    handles = [eng.submit(p, max_new_tokens=n) for p, n in pairs]
    eng.run_until_idle()
    for (p, n), h in zip(pairs, handles):
        ids, lens = L.left_pad_prompts([p])
        ref = np.asarray(L.generate(
            model, variables, np.asarray(ids), n,
            pad_lens=np.asarray(lens), pad_to=max_len))
        if h.result(1) != ref[0][int(lens[0]) + len(p):].tolist():
            return False
    return True


# ---------------------------------------------------------------------------
# stub mode (no jax compute — scheduler throughput during an outage)
# ---------------------------------------------------------------------------

def _run_stub(n_requests: int, num_slots: int, max_len: int,
              concurrencies, step_s: float, prefill_s: float) -> dict:
    from sparkdl_tpu.serving import GenerationEngine, StubBackend

    workload = make_workload(n_requests, vocab=32000)

    def make_engine():
        return GenerationEngine(
            StubBackend(num_slots, max_len, step_s=step_s,
                        prefill_s=prefill_s),
            min_bucket=_MIN_BUCKET, queue_capacity=max(64, n_requests))

    legs = {}
    for c in concurrencies:
        legs[str(c)] = run_engine_leg(make_engine, workload, c)

    # Static comparator with the SAME stub timings: whole batches, each
    # paying prefill once and max(out_lens) decode steps — slept PER
    # STEP, exactly as the engine's stub pays per step, so OS sleep
    # granularity inflates both sides equally and the ratio measures
    # scheduling (steps issued), not timer resolution.
    tokens = 0
    t0 = time.perf_counter()
    for i in range(0, len(workload), num_slots):
        grp = workload[i:i + num_slots]
        time.sleep(prefill_s)
        for _ in range(max(n for _, n in grp)):
            time.sleep(step_s)
        tokens += sum(n for _, n in grp)
    wall = time.perf_counter() - t0
    static = {"tokens": tokens, "wall_s": round(wall, 4),
              "tokens_s": round(tokens / wall, 2) if wall > 0 else None,
              "batches": -(-len(workload) // num_slots)}
    rec = {
        "mode": "stub",
        "step_s": step_s,
        "prefill_s": prefill_s,
        "num_slots": num_slots,
        "max_len": max_len,
        "requests": n_requests,
        "engine": legs,
        "static": static,
    }
    top = legs.get(str(max(concurrencies)), {})
    if top.get("tokens_s") and static.get("tokens_s"):
        rec["speedup_vs_static"] = round(
            top["tokens_s"] / static["tokens_s"], 2)
    return rec


def run(mode: str = "llama", rows: int | None = None) -> dict:
    """Bench entry point (``bench.py --worker serve`` / ``serve_stub``).
    Env knobs: BENCH_SERVE_REQUESTS / _SLOTS / _MAX_LEN /
    _CONCURRENCY (comma list) / _STUB_STEP_S."""
    n = rows or int(os.environ.get("BENCH_SERVE_REQUESTS", _DEF_REQUESTS))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", _DEF_SLOTS))
    max_len = int(os.environ.get("BENCH_SERVE_MAX_LEN", _DEF_MAX_LEN))
    conc = tuple(int(c) for c in os.environ.get(
        "BENCH_SERVE_CONCURRENCY", "1,8,32").split(",") if c)
    if mode == "stub":
        step_s = float(os.environ.get("BENCH_SERVE_STUB_STEP_S", "0.002"))
        return _run_stub(n, slots, max_len, conc, step_s, step_s)
    return _run_llama(n, slots, max_len, conc)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stub", action="store_true",
                    help="jax-free scheduler-only run (StubBackend)")
    ap.add_argument("--requests", type=int, default=None)
    ns = ap.parse_args(argv)
    if not ns.stub:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rec = run(mode="stub" if ns.stub else "llama", rows=ns.requests)
    print(json.dumps(rec, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
