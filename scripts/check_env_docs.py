#!/usr/bin/env python
"""Doc-drift lint: every ``SPARKDL_*`` env var referenced by the package
must be documented in the README (ISSUE 6 satellite).

PRs 1–5 grew ~30 ``SPARKDL_*`` knobs; each is one rename (or one new
knob) away from silently drifting out of the README's env-var tables.
This lint greps ``sparkdl_tpu/`` (plus ``bench.py`` and ``scripts/``)
for the pattern and fails loudly when any var is missing from
``README.md``. Stdlib-only, no imports of the package — it must run in
any environment, fast, as a tier-1 test (``tests/test_telemetry.py``)
and standalone in CI:

    python scripts/check_env_docs.py          # exit 1 + list on drift
"""

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_VAR_RE = re.compile(r"SPARKDL_[A-Z0-9_]+")
# Trailing fragments the regex over-matches in prose/format strings
# (e.g. "SPARKDL_FLASH_BLOCK_Q``/``_K" documents _K via ellipsis) are
# NOT special-cased: every var must appear verbatim in the README.


def code_env_vars(root: str = _REPO) -> set[str]:
    """Every SPARKDL_* name referenced by package/bench/scripts code."""
    out: set[str] = set()
    roots = [os.path.join(root, "sparkdl_tpu"),
             os.path.join(root, "scripts"),
             os.path.join(root, "bench.py")]
    for top in roots:
        if os.path.isfile(top):
            files = [top]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files += [os.path.join(dirpath, f) for f in filenames
                          if f.endswith(".py")]
        for path in files:
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    out.update(_VAR_RE.findall(f.read()))
            except OSError:
                continue
    return out


def documented_env_vars(readme: str | None = None) -> set[str]:
    readme = readme or os.path.join(_REPO, "README.md")
    try:
        with open(readme, encoding="utf-8", errors="replace") as f:
            return set(_VAR_RE.findall(f.read()))
    except OSError:
        return set()


def missing_vars(root: str = _REPO, readme: str | None = None) -> list[str]:
    """Vars referenced in code but absent from the README, sorted."""
    return sorted(code_env_vars(root) - documented_env_vars(readme))


def main() -> int:
    missing = missing_vars()
    if missing:
        print("check_env_docs: SPARKDL_* env vars referenced in code but "
              "missing from README.md:", file=sys.stderr)
        for v in missing:
            print(f"  {v}", file=sys.stderr)
        print("Document each in the README env-var tables (Observability "
              "/ Batch scoring pipeline / Environment variables).",
              file=sys.stderr)
        return 1
    n = len(code_env_vars())
    print(f"check_env_docs: ok — {n} SPARKDL_* vars all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
