#!/usr/bin/env python
"""Elastic gang supervision smoke (ISSUE 16 acceptance), end-to-end on CPU.

Two legs over one deterministic 12-batch GLOBAL dataset (leading dim 12 —
divisible by every world size the run passes through):

1. **Elastic run** — ``supervise(np=4, elastic=True, max_restarts=1)``
   launches a 4-rank training gang (``ListDataset(shard=True)`` over the
   global stream, checkpoint every 2 steps) with a chaos plan that
   ``decimate``\\ s rank 2 at step 5: the rank dies AND its slot stays dead
   — every later attempt at world size 4 re-kills it on entry. Expected
   recovery: budgeted restart after the first death → the relaunched rank
   2 dies again immediately → the supervisor correlates (same rank, same
   world size, consecutive) → **free shrink to 3** → the 3-rank gang
   restores the 4-rank checkpoint through the elastic reshard path and
   finishes. ``max_restarts=1`` makes completion itself the budget proof:
   if the shrink consumed budget the run would have given up instead.
   The batch ledger must show every batch consumed exactly once across
   the resize, with the ``world`` column switching 4 → 3.
2. **Counterfactual** — ``SPARKDL_ELASTIC=0``, the pre-ISSUE-16 behavior
   pinned: the same permanently dead rank death-loops the supervisor
   through its whole restart budget (``GangFailure: giving up``).

Also exports :func:`policy_block` — the jax-free policy-level version of
leg 1 (stdlib workers, same supervisor/chaos/ledger machinery) that
``bench.py`` runs to put an ``elastic`` block in failure_stats even when
the jax backend probe is down.

Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/elastic_smoke.py``
"""

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The supervisor never queries devices — the workers own the chips.
from sparkdl_tpu.runner.chaos import Fault, FaultPlan  # noqa: E402
from sparkdl_tpu.runner.data import read_ledger  # noqa: E402
from sparkdl_tpu.runner.launcher import (GangFailure,  # noqa: E402
                                         supervise)

N_BATCHES = 12     # one epoch, one batch per step
NUM_STEPS = 12
GLOBAL_ROWS = 12   # divisible by world sizes 4, 3, 2, 1
START_NP = 4
DEAD_RANK = 2
KILL_STEP = 5

_WORKER = """
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import optax
from sparkdl_tpu.runner import (ListDataset, XlaRunner,
                                softmax_cross_entropy_loss)

out_dir = sys.argv[1]
num_steps = int(sys.argv[2])
# np=-1 (default): size to whatever the launcher's env says — pinning a
# world size here would defeat the elastic relaunch.
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
params = {{"w": np.random.RandomState(0).randn(4, 3).astype(np.float32)}}
# GLOBAL batches (shard=True slices each rank's rows at draw time): the
# leading dim must divide evenly at every world size the gang visits.
batches = [{{"image": np.random.RandomState(i).randn({rows}, 4)
                 .astype(np.float32),
            "label": np.random.RandomState(i).randint(0, 3, ({rows},))}}
           for i in range({n_batches})]

res = runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"],
    data=ListDataset(batches, shard=True),
    num_steps=num_steps, checkpoint_every=2, log_every=1))
rank = os.environ.get("SPARKDL_PROCESS_ID", "0")
with open(os.path.join(out_dir, f"result_rank{{rank}}.jsonl"), "a") as f:
    f.write(json.dumps({{
        "final_step": int(res["state"].step),
        "final_loss": float(res["history"][-1]["loss"]),
        "world": int(os.environ.get("SPARKDL_NUM_PROCESSES", "1"))}})
        + "\\n")
"""

# Jax-free policy worker (bench's elastic block): the same supervisor /
# chaos / ledger machinery, progress persisted in a tiny state file
# instead of an orbax checkpoint. fire("worker") at entry gives a
# decimated slot its re-kill point even when no steps remain.
_POLICY_WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
from sparkdl_tpu.runner import chaos
from sparkdl_tpu.runner.data import append_ledger

out_dir = sys.argv[1]
num_steps = int(sys.argv[2])
chaos.fire("worker")
rank = int(os.environ.get("SPARKDL_PROCESS_ID", "0"))
state_path = os.path.join(out_dir, "progress.json")
start = 0
try:
    with open(state_path) as f:
        start = int(json.load(f)["step"])
except (OSError, ValueError, KeyError):
    pass
for step in range(start, num_steps):
    chaos.fire("step_start", step=step)
    if rank == 0:
        append_ledger(step, {{"epoch": 0, "batch_index": step + 1,
                              "skip_list": []}})
        tmp = state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({{"step": step + 1}}, f)
        os.replace(tmp, state_path)
"""


def _write(out_dir: str, name: str, body: str, **fmt) -> str:
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        f.write(body.format(repo=_REPO, **fmt))
    return path


def _audit_ledger(ledger_dir: str, num_steps: int, n_batches: int):
    """Exactly-once audit over rank 0's ledger (shard=True: batch indices
    are GLOBAL, so one rank's ledger describes the whole gang). Returns
    (exactly_once, replay_consistent, worlds_seen)."""
    ledger = read_ledger(ledger_dir)
    by_step: dict = {}
    replay_consistent = True
    for e in ledger:
        step, bi = e["step"], e["batch_index"]
        prev = by_step.get(step)
        if prev is not None and prev != bi \
                and prev not in (e.get("skip_list") or []):
            replay_consistent = False
        by_step[step] = bi
    consumed = sorted(by_step.values())
    exactly_once = (consumed == list(range(n_batches))
                    and sorted(by_step) == list(range(num_steps)))
    worlds = sorted({e.get("world") for e in ledger if e.get("world")})
    return exactly_once, replay_consistent, worlds


def _decimate_plan() -> FaultPlan:
    return FaultPlan([
        Fault("step_start", "decimate", at_step=KILL_STEP, rank=DEAD_RANK)])


def main() -> int:
    checks: dict = {}
    worker_env = {"JAX_PLATFORMS": "cpu",
                  "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}

    # -- 1. elastic: permanent rank death -> free shrink -> completion ----
    out_dir = tempfile.mkdtemp(prefix="sparkdl-elastic-smoke-")
    ledger_dir = os.path.join(out_dir, "ledger")
    worker = _write(out_dir, "worker.py", _WORKER,
                    n_batches=N_BATCHES, rows=GLOBAL_ROWS)
    res = supervise(worker, np=START_NP, args=[out_dir, str(NUM_STEPS)],
                    env={**worker_env, "SPARKDL_BATCH_LEDGER": ledger_dir},
                    plan=_decimate_plan(), elastic=True,
                    max_restarts=1,  # completion proves the resize was free
                    timeout_s=300.0, backoff_s=0.1, poll_s=0.25)
    survivors = []
    for r in range(START_NP - 1):
        path = os.path.join(out_dir, f"result_rank{r}.jsonl")
        if os.path.exists(path):
            survivors += [json.loads(ln) for ln in open(path)]
    checks["job_completed_at_ws3"] = (
        len(survivors) == START_NP - 1
        and all(s["final_step"] == NUM_STEPS and s["world"] == START_NP - 1
                for s in survivors))
    checks["supervisor_resized"] = (
        res.resizes == 1 and res.final_np == START_NP - 1
        and "resized" in res.failure_kinds)
    checks["resize_was_free"] = res.restarts == 2  # 2 relaunches, budget 1
    degr_names = {d.get("name") for d in res.degradations}
    checks["degradations_narrate_resize"] = (
        "gang_resized" in degr_names and "train_resume" in degr_names
        and "checkpoint_resharded" in degr_names)

    exactly_once, replay_consistent, worlds = _audit_ledger(
        ledger_dir, NUM_STEPS, N_BATCHES)
    checks["ledger_exactly_once_across_resize"] = exactly_once
    checks["ledger_replay_deterministic"] = replay_consistent
    checks["ledger_records_resize"] = worlds == [START_NP - 1, START_NP]

    # -- 2. counterfactual: SPARKDL_ELASTIC=0 exhausts the budget ---------
    cf_dir = tempfile.mkdtemp(prefix="sparkdl-elastic-smoke-cf-")
    cf_worker = _write(cf_dir, "worker.py", _WORKER,
                       n_batches=N_BATCHES, rows=GLOBAL_ROWS)
    try:
        supervise(cf_worker, np=START_NP, args=[cf_dir, str(NUM_STEPS)],
                  env={**worker_env, "SPARKDL_ELASTIC": "0"},
                  plan=_decimate_plan(), max_restarts=2,
                  timeout_s=300.0, backoff_s=0.1, poll_s=0.25)
        checks["counterfactual_death_loops"] = False
    except GangFailure as e:
        checks["counterfactual_death_loops"] = "giving up after 2" in str(e)

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok, **checks,
        "restarts": res.restarts,
        "failure_kinds": res.failure_kinds,
        "resizes": res.resizes,
        "final_np": res.final_np,
        "ledger_worlds": worlds,
        "out_dir": out_dir,
    }))
    return 0 if ok else 1


def policy_block(np_: int = 3, num_steps: int = 8,
                 dead_rank: int = 1) -> dict:
    """Jax-free elastic policy exercise for BENCH records: a stdlib
    worker gang loses ``dead_rank`` permanently (``decimate``), the
    supervisor shrinks, the batch ledger is audited. Returns the
    ``elastic`` failure_stats block: resizes, final world size,
    exactly-once verdict — present even when the jax backend probe is
    down, because nothing here touches jax."""
    out_dir = tempfile.mkdtemp(prefix="sparkdl-elastic-policy-")
    ledger_dir = os.path.join(out_dir, "ledger")
    worker = _write(out_dir, "worker.py", _POLICY_WORKER)
    plan = FaultPlan([Fault("step_start", "decimate",
                            at_step=num_steps // 2, rank=dead_rank)])
    res = supervise(worker, np=np_, args=[out_dir, str(num_steps)],
                    env={"SPARKDL_BATCH_LEDGER": ledger_dir},
                    plan=plan, elastic=True, max_restarts=2,
                    timeout_s=60.0, backoff_s=0.05, poll_s=0.1)
    exactly_once, replay_consistent, worlds = _audit_ledger(
        ledger_dir, num_steps, num_steps)
    return {"resizes": res.resizes, "final_np": res.final_np,
            "start_np": np_, "restarts": res.restarts,
            "exactly_once": bool(exactly_once and replay_consistent),
            "ledger_worlds": worlds}


if __name__ == "__main__":
    sys.exit(main())
