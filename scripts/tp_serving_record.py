#!/usr/bin/env python
"""Tensor-parallel serving MULTICHIP record (ISSUE 14 acceptance).

Drives the full serving composition — paged KV + radix grafts ×
chunked prefill × speculative decoding × preemption-resume — through
tensor-parallel engines at tp ∈ {1, 2, 4} on the 8-virtual-device CPU
mesh (the same host-platform validation surface as the driver's
multichip dryrun), and writes a ``MULTICHIP_r<N>.json``-style record
proving:

- greedy output at every tp degree is TOKEN-IDENTICAL to the
  single-device engine AND to static ``generate()`` — including a
  mid-decode preemption whose resume must continue bit-exactly;
- zero decode/verify re-traces after warmup (compile-cache signatures);
- per-device KV pool bytes measured at ~``1/tp`` of the tp=1 engine.

Output auto-numbering follows ``scripts/probe_loop.sh``: the record is
written to the next FREE ``MULTICHIP_r<N>.json`` at the repo root (git
does not preserve mtimes, so reusing a name would mis-rank the
records; ``--out`` overrides). And — the r05 lesson, where an
injected-chaos traceback sat undifferentiated in the tail — the record
SEPARATES fault-injection evidence from real failures: the chaos leg's
deliberately injected retryable restart lands under
``injected_chaos`` (``expected: true``), anything else under
``failures``; ``ok`` means "no REAL failure", not "no restart ever
happened".

Run:  python scripts/tp_serving_record.py [--out PATH] [--degrees 1,2,4]
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_DEVICES = 8


def _force_virtual_devices():
    """8 virtual CPU devices, latched before any backend initializes
    (the sitecustomize pre-imports jax, so the env var alone is not
    enough — go through jax.config exactly like tests/conftest.py)."""
    from sparkdl_tpu.runner.launcher import host_device_flags
    os.environ["XLA_FLAGS"] = host_device_flags(
        os.environ.get("XLA_FLAGS", ""), N_DEVICES)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def next_multichip_path(root: str = _REPO) -> str:
    """The next free ``MULTICHIP_r<N>.json`` (probe_loop.sh-style
    auto-numbering — never clobber or mis-rank an earlier record)."""
    n = 1
    while True:
        p = os.path.join(root, f"MULTICHIP_r{n:02d}.json")
        if not os.path.exists(p):
            return p
        n += 1


def _tp_config():
    """The serve_bench tp-leg model (num_kv_heads=4: exact head split
    at tp=4) — ONE definition, imported from the bench script so the
    record and the bench leg cannot drift apart."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(_REPO, "scripts", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod._tp_config()


def _drive_one_degree(GenerationEngine, GLOBAL_COMPILE_CACHE,
                      HistoryDraft, model, variables, tp, max_len, new,
                      pa, pb, refs):
    """One degree's composition drive: chunked prefill → speculative
    decode → forced mid-decode preemption → resumed + grafted streams.
    Returns (streams, snapshot, engine, (sig_d, sig_v))."""
    prov = HistoryDraft()
    prov.observe(pa, refs[0])  # warm retrieval: high-acceptance
    prov.observe(pb, refs[1])  # verify windows on every iteration
    eng = GenerationEngine.from_model(
        model, variables, num_slots=2, max_len=max_len,
        prefill_chunk=8, block_size=8, prefill_budget=16, spec_k=3,
        draft_provider=prov, tp=tp)
    ha = eng.submit(pa, max_new_tokens=new)
    eng.step()   # 2 of pa's 3 chunks (budget 16)
    eng.step()   # final chunk + first token (+ a verify window)
    eng.step()   # >= 1 speculative verify
    sig_d = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
    sig_v = GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
    assert ha.state == "running" and 0 < len(ha.tokens) < new
    eng._preempt_newest([(ha.slot, ha)])   # forced preemption
    hb = eng.submit(pb, max_new_tokens=new)  # grafts pa's head
    eng.run_until_idle()
    return ([ha.result(1), hb.result(1)], eng.snapshot(), eng,
            (sig_d, sig_v))


def run_tp_composition(degrees, tail: list, failures: list) -> dict:
    """The ISSUE 14 acceptance drive (see module doc). Degrees the
    visible devices cannot host are skipped with a recorded reason,
    and one degree's failure lands in ``failures`` without discarding
    the other degrees' already-measured evidence."""
    import jax
    import numpy as np

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine
    from sparkdl_tpu.serving.draft import HistoryDraft

    cfg = _tp_config()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    rng = np.random.RandomState(7)
    max_len, new = 64, 12
    head = rng.randint(0, cfg.vocab_size, 16).tolist()  # 2 radix blocks
    pa = head + rng.randint(0, cfg.vocab_size, 3).tolist()
    pb = head + rng.randint(0, cfg.vocab_size, 6).tolist()

    # static generate() references — the ground truth every engine
    # (every tp degree, through every composition layer) must hit
    ids, lens = L.left_pad_prompts([pa, pb])
    ref_out = np.asarray(L.generate(model, variables, np.asarray(ids),
                                    new, pad_lens=np.asarray(lens),
                                    pad_to=max_len))
    refs = [ref_out[i][int(lens[i]) + len(p):].tolist()
            for i, p in enumerate([pa, pb])]

    n_dev = len(jax.devices())
    usable, skipped = [], []
    for d in degrees:
        if d > n_dev:
            skipped.append({"degree": d,
                            "reason": f"needs {d} devices, {n_dev} "
                                      f"visible"})
        else:
            usable.append(d)
    degrees = usable
    out: dict = {"degrees": {}, "skipped_degrees": skipped, "config": {
        "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
        "num_kv_heads": cfg.num_kv_heads,
        "composition": ["paged block tables", "radix prefix graft",
                        "chunked prefill (budget 16, chunk 8)",
                        "speculative decode k=3 (HistoryDraft)",
                        "mid-decode preemption-resume"]}}
    streams: dict = {}
    for tp in degrees:
        try:
            streams[tp], snap, eng, sigs = _drive_one_degree(
                GenerationEngine, GLOBAL_COMPILE_CACHE, HistoryDraft,
                model, variables, tp, max_len, new, pa, pb, refs)
        except Exception as e:  # noqa: BLE001 — one degree's failure
            # must not discard the others' already-measured evidence
            failures.append({"leg": f"tp={tp}",
                             "error": f"{type(e).__name__}: {e}"[:500]})
            tail.append(f"tp={tp}: FAILED ({type(e).__name__})")
            continue
        sig_d, sig_v = sigs
        leg = {
            "tp_degree": tp,
            "identical_to_static": streams[tp] == refs,
            "kv_pool_device_bytes": eng.kv_pool_device_bytes,
            "decode_retrace_after_warmup":
                GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")
                - sig_d,
            "verify_retrace_after_warmup":
                GLOBAL_COMPILE_CACHE.signatures("serve_verify_step")
                - sig_v,
            "preemptions": snap["preemptions"],
            "spec_verifies": snap["spec_verifies"],
            "spec_tokens_accepted": snap["spec_tokens_accepted"],
            "prefix_hits": (snap.get("prefix_cache") or {}).get("hits"),
        }
        out["degrees"][str(tp)] = leg
        tail.append(
            f"tp={tp}: identical_to_static={leg['identical_to_static']} "
            f"preemptions={leg['preemptions']} "
            f"spec_verifies={leg['spec_verifies']} "
            f"kv_pool_device_bytes={leg['kv_pool_device_bytes']} "
            f"retraces={leg['decode_retrace_after_warmup'] + leg['verify_retrace_after_warmup']}")
    # ONE measured degree is no cross-degree evidence: report None,
    # never a vacuous True (serve_bench's tp leg applies the same rule)
    if len(streams) >= 2:
        base = streams[min(streams)]
        out["tp_identical_across_degrees"] = all(
            s == base for s in streams.values())
    else:
        out["tp_identical_across_degrees"] = None
    out["tp_identical_to_static"] = all(
        d["identical_to_static"] for d in out["degrees"].values()) \
        if out["degrees"] else None
    out["retraces_after_warmup"] = sum(
        d["decode_retrace_after_warmup"] + d["verify_retrace_after_warmup"]
        for d in out["degrees"].values())
    bytes_by_tp = {k: d["kv_pool_device_bytes"]
                   for k, d in out["degrees"].items()}
    out["kv_pool_device_bytes"] = bytes_by_tp
    b1 = bytes_by_tp.get("1")
    if b1:
        out["kv_pool_device_frac"] = {
            k: round(v / b1, 4) for k, v in bytes_by_tp.items()}
    return out


def run_chaos_leg(tail: list) -> dict:
    """One DELIBERATE retryable failure absorbed by supervision — the
    fault-injection leg every multichip record carries, now labeled as
    such so its traceback can never read as a real failure (the r05
    lesson)."""
    import numpy as np
    import optax

    from sparkdl_tpu.runner import XlaRunner, softmax_cross_entropy_loss

    rng = np.random.RandomState(11)
    params = {"w": rng.randn(4, 3).astype(np.float32) * 0.1}
    batch = {"image": rng.randn(4, 4).astype(np.float32),
             "label": rng.randint(0, 3, (4,))}
    attempts = []

    def data(n_ok):
        def gen():
            from sparkdl_tpu.runner.chaos import announce_injection
            for i in range(3):
                if n_ok is not None and i == n_ok:
                    announce_injection()
                    raise RuntimeError("injected chip failure")
                yield batch
        return gen()

    def flaky(ctx):
        attempts.append(1)
        return ctx.fit(data=data(2 if len(attempts) == 1 else None),
                       num_steps=3,
                       loss_fn=softmax_cross_entropy_loss(),
                       params=params, tx=optax.sgd(0.1),
                       apply_fn=lambda p, x: x @ p["w"], log_every=100)

    res = XlaRunner(np=1).run_with_restarts(flaky, max_restarts=2,
                                            backoff_s=0.0)
    entry = {"kind": "retryable", "expected": True,
             "injected": "chip failure at batch 2 of attempt 1",
             "restarts": len(attempts) - 1,
             "recovered": int(res["state"].step) == 3}
    tail.append(f"chaos leg: injected retryable restart absorbed "
                f"(restarts={entry['restarts']}, "
                f"recovered={entry['recovered']}) — EXPECTED")
    return entry


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="output path (default: next free "
                         "MULTICHIP_r<N>.json)")
    ap.add_argument("--degrees", default="1,2,4")
    ap.add_argument("--skip-chaos", action="store_true")
    ns = ap.parse_args(argv)
    # Acceptance evidence must not bend to ambient serving knobs —
    # the shared hygiene helper (see its docstring); process-wide by
    # design, this script IS the measurement process.
    from sparkdl_tpu.serving.engine import scrub_serving_env
    scrub_serving_env()
    jax = _force_virtual_devices()
    degrees = [int(d) for d in ns.degrees.split(",") if d]
    tail: list = []
    rec: dict = {"kind": "tp_serving", "n_devices": len(jax.devices()),
                 "platform": jax.default_backend(),
                 "honest_label": (
                     "8 virtual CPU devices: multi-chip SEMANTICS "
                     "(identity, re-traces, 1/tp per-device KV bytes) "
                     "— not wall-clock speedup"),
                 "injected_chaos": [], "failures": []}
    try:
        rec.update(run_tp_composition(degrees, tail, rec["failures"]))
    except Exception as e:  # noqa: BLE001 — a real failure is the record
        rec["failures"].append(
            {"leg": "tp_composition",
             "error": f"{type(e).__name__}: {e}"[:500]})
    if not ns.skip_chaos:
        try:
            rec["injected_chaos"].append(run_chaos_leg(tail))
        except Exception as e:  # noqa: BLE001
            rec["failures"].append(
                {"leg": "chaos",
                 "error": f"{type(e).__name__}: {e}"[:500]})
    bytes_by_tp = rec.get("kv_pool_device_bytes") or {}
    shrink_exact = bool(bytes_by_tp) and all(
        bytes_by_tp.get("1", 0) == v * int(k)
        for k, v in bytes_by_tp.items()) if "1" in bytes_by_tp else None
    rec["kv_pool_device_shrink_exact"] = shrink_exact
    # ok means "no real failure AND nothing measured contradicted the
    # claims" — None fields (a single measured degree has no
    # cross-degree evidence, no tp=1 no shrink baseline) are honest
    # gaps stated in the record, not failures; False anywhere is.
    rec["ok"] = (not rec["failures"]
                 and rec.get("tp_identical_to_static") is True
                 and rec.get("tp_identical_across_degrees") is not False
                 and rec.get("retraces_after_warmup") == 0
                 and shrink_exact is not False)
    rec["skipped"] = False
    rec["tail"] = "\n".join(tail)
    out_path = ns.out or next_multichip_path()
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")
    print(json.dumps({"ok": rec["ok"], "out": out_path,
                      "failures": rec["failures"]}))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
