#!/usr/bin/env python
"""Train-resume chaos smoke: the exactly-once training data plane
(ISSUE 5 acceptance), end-to-end through the supervisor on CPU.

Three legs over one deterministic 13-batch dataset:

1. **Supervised run** — ``supervise()`` launches a single-rank training
   worker (ListDataset, ``feed_lookahead=2``, checkpoint every 2 steps)
   with a chaos plan injecting (a) one SIGKILL at step 5 (fires once,
   persisted via the plan state_dir) and (b) a deterministic **poison
   batch**: batch index 8 NaN-poisoned at the ``data_fetch`` site on
   every attempt. Expected recovery: retryable restart after the SIGKILL
   → resume at the exact batch; fatal ``TrainingDivergedError`` at batch
   8 → one probe restart → same signature again → batch 8 quarantined
   onto the skip-list → final attempt finishes. The batch-id ledger
   (``SPARKDL_BATCH_LEDGER``) must show every step consuming the same
   batch in every attempt that executed it (deterministic replay — the
   lookahead batches were replayed, not dropped) and batches 0..12 minus
   {8} each consumed by exactly one step. ``SuperviseResult.degradations``
   must name both the restart-resume (``train_resume``) and the
   ``train_batch_quarantined`` events.
2. **Clean run** — same worker, no chaos, skip-list pre-seeded to {8}:
   its final loss must equal the supervised run's exactly (same batch
   lineage ⇒ same floats — the strongest exactly-once proof).
3. **Counterfactual** — the pre-ISSUE-5 behavior, pinned: the same poison
   batch shaped as a retryable fault with ``quarantine_batches=False``
   death-loops the supervisor through its whole restart budget
   (``GangFailure: giving up``).

Prints one JSON line and exits 0 on success.

Run: ``JAX_PLATFORMS=cpu python scripts/train_resume_smoke.py``
"""

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The supervisor never queries devices — the workers own the chips.
from sparkdl_tpu.runner.chaos import Fault, FaultPlan  # noqa: E402
from sparkdl_tpu.runner.data import read_ledger  # noqa: E402
from sparkdl_tpu.runner.launcher import (GangFailure,  # noqa: E402
                                         supervise)

N_BATCHES = 13
NUM_STEPS = 12
POISON_BATCH = 8

_WORKER = """
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import optax
from sparkdl_tpu.runner import (ListDataset, XlaRunner,
                                softmax_cross_entropy_loss)

out_dir = sys.argv[1]
num_steps = int(sys.argv[2])
runner = XlaRunner(checkpoint_dir=os.path.join(out_dir, "ckpt"))
params = {{"w": np.random.RandomState(0).randn(4, 3).astype(np.float32)}}
batches = [{{"image": np.random.RandomState(i).randn(8, 4)
                 .astype(np.float32),
            "label": np.random.RandomState(i).randint(0, 3, (8,))}}
           for i in range({n_batches})]

res = runner.run(lambda ctx: ctx.fit(
    loss_fn=softmax_cross_entropy_loss(), params=params, tx=optax.sgd(0.1),
    apply_fn=lambda p, x: x @ p["w"], data=ListDataset(batches),
    num_steps=num_steps, checkpoint_every=2, log_every=1,
    feed_lookahead=2))
with open(os.path.join(out_dir, "result.jsonl"), "a") as f:
    f.write(json.dumps({{
        "final_step": int(res["state"].step),
        "final_loss": float(res["history"][-1]["loss"]),
        "steps_this_attempt": res["meter"].steps}}) + "\\n")
"""


def _write_worker(out_dir: str) -> str:
    worker = os.path.join(out_dir, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER.format(repo=_REPO, n_batches=N_BATCHES))
    return worker


def _run_leg(name: str, **kw):
    out_dir = tempfile.mkdtemp(prefix=f"sparkdl-resume-smoke-{name}-")
    worker = _write_worker(out_dir)
    res = supervise(worker, np=1, args=[out_dir, str(NUM_STEPS)],
                    timeout_s=300.0, backoff_s=0.1, poll_s=0.25, **kw)
    return out_dir, res


def main() -> int:
    checks: dict = {}

    # -- 1. supervised: SIGKILL + deterministic poison batch --------------
    plan = FaultPlan([
        Fault("step_start", "sigkill", at_step=5),
        Fault("data_fetch", "poison", at_step=POISON_BATCH, once=False),
    ])
    ledger_dir = tempfile.mkdtemp(prefix="sparkdl-resume-ledger-")
    out_dir, res = _run_leg("supervised", max_restarts=3, plan=plan,
                            env={"SPARKDL_BATCH_LEDGER": ledger_dir})
    results = [json.loads(ln) for ln in open(
        os.path.join(out_dir, "result.jsonl"))]
    degr_names = {d.get("name") for d in res.degradations}
    checks["job_completed"] = (
        len(results) == 1 and results[0]["final_step"] == NUM_STEPS)
    checks["quarantined_batches"] = res.quarantined_batches == [POISON_BATCH]
    checks["kinds_show_recovery"] = "quarantined" in res.failure_kinds
    checks["degradations_narrate_resume_and_quarantine"] = (
        "train_resume" in degr_names
        and "train_batch_quarantined" in degr_names)

    # -- exactly-once ledger audit ----------------------------------------
    # Across ALL attempts (the ledger is append-mode, chronological):
    # every step that executed consumed the SAME batch in every attempt —
    # deterministic replay; the lookahead batches drawn before the
    # SIGKILL were replayed, not dropped — with exactly one legal remap:
    # a step may move off a batch that was quarantined in between (the
    # entry's skip_list records the context). The final step→batch
    # mapping must cover every batch exactly once, minus the quarantined
    # one: no replays into the surviving lineage, no gaps.
    ledger = read_ledger(ledger_dir)
    by_step: dict = {}
    replay_consistent = True
    for e in ledger:
        step, bi = e["step"], e["batch_index"]
        prev = by_step.get(step)
        if prev is not None and prev != bi \
                and prev not in (e.get("skip_list") or []):
            replay_consistent = False
        by_step[step] = bi
    consumed = sorted(by_step.values())
    expected = [i for i in range(N_BATCHES) if i != POISON_BATCH]
    checks["ledger_replay_deterministic"] = replay_consistent
    checks["ledger_exactly_once"] = (
        consumed == expected
        and sorted(by_step) == list(range(NUM_STEPS)))

    # -- 2. clean run on the same skip-list: identical final loss ---------
    clean_dir, clean_res = _run_leg(
        "clean", max_restarts=0,
        env={"SPARKDL_SKIP_BATCHES": json.dumps([POISON_BATCH])})
    clean = [json.loads(ln) for ln in open(
        os.path.join(clean_dir, "result.jsonl"))]
    checks["clean_run_restartless"] = clean_res.restarts == 0
    checks["final_loss_matches_clean_run"] = (
        len(clean) == 1
        and clean[0]["final_loss"] == results[0]["final_loss"])

    # -- 3. counterfactual: no skip-list => restart-budget death-loop -----
    cf_plan = FaultPlan([
        Fault("data_fetch", "preempt", at_step=POISON_BATCH, once=False)])
    try:
        _run_leg("counterfactual", max_restarts=2, plan=cf_plan,
                 quarantine_batches=False)
        checks["counterfactual_death_loops"] = False
    except GangFailure as e:
        checks["counterfactual_death_loops"] = "giving up after 2" in str(e)

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok, **checks,
        "restarts": res.restarts,
        "failure_kinds": res.failure_kinds,
        "final_loss": results[0]["final_loss"] if results else None,
        "ledger_steps": len(by_step),
        "out_dir": out_dir,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
