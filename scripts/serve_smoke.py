#!/usr/bin/env python
"""Serving smoke: concurrent submitters against a live engine (ISSUE 8).

End-to-end proof on CPU with ``LlamaConfig.tiny``:

1. N closed-loop clients submit mixed-length requests concurrently into
   a background-threaded engine; **every request completes** (nothing
   starves — the queue is FIFO and slots refill independently);
2. aggregate tokens/s at concurrency > single-stream tokens/s on the
   same workload (the continuous-batching point);
3. the compiled decode step is **never re-traced** once warm
   (``GLOBAL_COMPILE_CACHE.signatures``);
4. greedy engine output is token-identical to the static ``generate()``
   path;
5. ISSUE 18 quant leg: the paged engine at ``kv_dtype=int8`` +
   ``weight_dtype=int8`` vs the paged f32 engine — greedy streams
   within the documented tolerance gate (mean longest-common-prefix
   fraction >= 0.8 — int8 rounding may legitimately flip a late token
   on the random tiny model, full divergence may not), and the
   speculative accept-rate delta is reported for bench_trend gating.

The closed-loop client harness is ``serve_bench.run_engine_leg`` — ONE
driver shared with the bench, so smoke and bench cannot disagree on
how a workload is offered.

Wired as a slow test in tests/test_serving.py (run in-process — the
tier-1 lean rule); standalone:

    JAX_PLATFORMS=cpu python scripts/serve_smoke.py
"""

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _serve_bench():
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(_REPO, "scripts", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def quant_block(n_requests: int = 24) -> dict:
    """ISSUE 18 quant evidence leg (``bench.py`` failure_stats rides
    this, like ``elastic_smoke.policy_block``): a paged + speculative
    tiny-llama engine at ``kv_dtype=int8`` + ``weight_dtype=int8`` vs
    the same engine at f32, on CPU.

    Returns the greedy-stream agreement (mean longest-common-prefix
    fraction — the documented gate is >= 0.8: a late rounding-flipped
    token is legitimate quantization noise, wholesale divergence is a
    bug), the speculative accept-rate pair + delta (the end-to-end
    quality monitor), and the pool-blocks multiplier at equal
    ``kv_pool_mb`` (the capacity win pool_stats proves)."""
    import jax

    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    sb = _serve_bench()
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    rng = np.random.RandomState(7)
    workload = [(rng.randint(0, cfg.vocab_size,
                             size=int(rng.choice((2, 5, 9)))).tolist(),
                 int(rng.choice((3, 5, 24), p=(0.5, 0.3, 0.2))))
                for _ in range(n_requests)]

    def make_engine(kv=None, wq=None, **kw):
        return GenerationEngine.from_model(
            model, variables, num_slots=4, max_len=128,
            block_size=16, kv_dtype=kv, weight_dtype=wq,
            spec_k=2, min_bucket=8, queue_capacity=64, **kw)

    leg_f = sb.run_engine_leg(lambda: make_engine(), workload, 4)
    leg_q = sb.run_engine_leg(lambda: make_engine("int8", "int8"),
                              workload, 4)

    def streams(make):
        eng = make()
        hs = [eng.submit(p, max_new_tokens=n)
              for p, n in workload[:6]]
        eng.run_until_idle()
        return [h.result(1) for h in hs]

    fracs = []
    for a, b in zip(streams(lambda: make_engine()),
                    streams(lambda: make_engine("int8", "int8"))):
        lcp = 0
        for x, y in zip(a, b):
            if x != y:
                break
            lcp += 1
        fracs.append(lcp / max(1, max(len(a), len(b))))
    accept_f = leg_f.get("spec_accept_rate")
    accept_q = leg_q.get("spec_accept_rate")
    # capacity win at EQUAL pool MB — construction only, nothing runs
    bf = make_engine(kv_pool_mb=1.0).backend.pool_stats()["blocks_total"]
    bq = make_engine("int8", kv_pool_mb=1.0) \
        .backend.pool_stats()["blocks_total"]
    return {
        "kv_dtype": "int8", "weight_dtype": "int8",
        "requests": n_requests,
        "completed_f32": leg_f.get("completed"),
        "completed_int8": leg_q.get("completed"),
        "token_match_frac": round(sum(fracs) / len(fracs), 4),
        "accept_rate_f32": accept_f,
        "accept_rate_int8": accept_q,
        "accept_rate_delta": round(abs(accept_f - accept_q), 4)
        if accept_f is not None and accept_q is not None else None,
        "effective_blocks_x": round(bq / bf, 2) if bf else None,
    }


def main() -> int:
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    sb = _serve_bench()
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    num_slots, max_len = 4, 128
    rng = np.random.RandomState(7)
    workload = [(rng.randint(0, cfg.vocab_size,
                             size=int(rng.choice((2, 5, 9)))).tolist(),
                 int(rng.choice((3, 5, 24), p=(0.5, 0.3, 0.2))))
                for _ in range(24)]

    def make_engine():
        return GenerationEngine.from_model(
            model, variables, num_slots=num_slots, max_len=max_len,
            min_bucket=8, queue_capacity=64)

    # warm every program (buckets 8/16 + the decode step), then pin sigs
    warm = sb.run_engine_leg(make_engine, workload[:4], 4)
    assert warm["completed"] == 4, warm
    sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

    single = sb.run_engine_leg(make_engine, workload, 1)
    multi = sb.run_engine_leg(make_engine, workload, 8)

    # 1) nothing starves — every request completed, both legs
    assert single["completed"] == len(workload), single
    assert multi["completed"] == len(workload), multi
    # 2) concurrency beats single-stream aggregate tokens/s
    assert multi["tokens_s"] > single["tokens_s"], (multi, single)
    # 3) steady state never re-traced the decode step
    retrace = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step") \
        - sig_decode
    assert retrace == 0, f"decode step re-traced {retrace}x"
    # 4) greedy token identity vs the static path (inline drive)
    eng = make_engine()
    handles = [eng.submit(p, max_new_tokens=n) for p, n in workload[:3]]
    eng.run_until_idle()
    for (prompt, new), h in zip(workload[:3], handles):
        ids, lens = L.left_pad_prompts([prompt])
        ref = np.asarray(L.generate(model, variables, ids, new,
                                    pad_lens=lens, pad_to=max_len))[0]
        want = ref[int(lens[0]) + len(prompt):].tolist()
        assert h.result(1) == want, (prompt, h.tokens, want)

    # 5) ISSUE 18 quant leg (see quant_block): greedy tolerance gate +
    # accept-rate delta + >= 2x pool blocks at equal MB.
    quant = quant_block(n_requests=len(workload))
    assert quant["completed_f32"] == quant["requests"], quant
    assert quant["completed_int8"] == quant["requests"], quant
    assert quant["token_match_frac"] >= 0.8, \
        f"int8 greedy streams diverged: {quant}"
    assert quant["effective_blocks_x"] >= 2.0, \
        f"int8 pool bought < 2x blocks at equal MB: {quant}"

    print(json.dumps({
        "ok": True, "requests": len(workload),
        "single_stream_tokens_s": single["tokens_s"],
        "concurrent_tokens_s": multi["tokens_s"],
        "speedup": round(multi["tokens_s"] / single["tokens_s"], 2),
        "decode_retraces": retrace, "token_identical": True,
        "quant": quant}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
