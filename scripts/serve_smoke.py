#!/usr/bin/env python
"""Serving smoke: concurrent submitters against a live engine (ISSUE 8).

End-to-end proof on CPU with ``LlamaConfig.tiny``:

1. N closed-loop clients submit mixed-length requests concurrently into
   a background-threaded engine; **every request completes** (nothing
   starves — the queue is FIFO and slots refill independently);
2. aggregate tokens/s at concurrency > single-stream tokens/s on the
   same workload (the continuous-batching point);
3. the compiled decode step is **never re-traced** once warm
   (``GLOBAL_COMPILE_CACHE.signatures``);
4. greedy engine output is token-identical to the static ``generate()``
   path.

The closed-loop client harness is ``serve_bench.run_engine_leg`` — ONE
driver shared with the bench, so smoke and bench cannot disagree on
how a workload is offered.

Wired as a slow test in tests/test_serving.py (run in-process — the
tier-1 lean rule); standalone:

    JAX_PLATFORMS=cpu python scripts/serve_smoke.py
"""

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _serve_bench():
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(_REPO, "scripts", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    import jax

    from sparkdl_tpu.core.runtime import GLOBAL_COMPILE_CACHE
    from sparkdl_tpu.models import llama as L
    from sparkdl_tpu.serving import GenerationEngine

    sb = _serve_bench()
    cfg = L.LlamaConfig.tiny()
    model = L.LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 4), np.int32))
    num_slots, max_len = 4, 128
    rng = np.random.RandomState(7)
    workload = [(rng.randint(0, cfg.vocab_size,
                             size=int(rng.choice((2, 5, 9)))).tolist(),
                 int(rng.choice((3, 5, 24), p=(0.5, 0.3, 0.2))))
                for _ in range(24)]

    def make_engine():
        return GenerationEngine.from_model(
            model, variables, num_slots=num_slots, max_len=max_len,
            min_bucket=8, queue_capacity=64)

    # warm every program (buckets 8/16 + the decode step), then pin sigs
    warm = sb.run_engine_leg(make_engine, workload[:4], 4)
    assert warm["completed"] == 4, warm
    sig_decode = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step")

    single = sb.run_engine_leg(make_engine, workload, 1)
    multi = sb.run_engine_leg(make_engine, workload, 8)

    # 1) nothing starves — every request completed, both legs
    assert single["completed"] == len(workload), single
    assert multi["completed"] == len(workload), multi
    # 2) concurrency beats single-stream aggregate tokens/s
    assert multi["tokens_s"] > single["tokens_s"], (multi, single)
    # 3) steady state never re-traced the decode step
    retrace = GLOBAL_COMPILE_CACHE.signatures("serve_decode_step") \
        - sig_decode
    assert retrace == 0, f"decode step re-traced {retrace}x"
    # 4) greedy token identity vs the static path (inline drive)
    eng = make_engine()
    handles = [eng.submit(p, max_new_tokens=n) for p, n in workload[:3]]
    eng.run_until_idle()
    for (prompt, new), h in zip(workload[:3], handles):
        ids, lens = L.left_pad_prompts([prompt])
        ref = np.asarray(L.generate(model, variables, ids, new,
                                    pad_lens=lens, pad_to=max_len))[0]
        want = ref[int(lens[0]) + len(prompt):].tolist()
        assert h.result(1) == want, (prompt, h.tokens, want)

    print(json.dumps({
        "ok": True, "requests": len(workload),
        "single_stream_tokens_s": single["tokens_s"],
        "concurrent_tokens_s": multi["tokens_s"],
        "speedup": round(multi["tokens_s"] / single["tokens_s"], 2),
        "decode_retraces": retrace, "token_identical": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
